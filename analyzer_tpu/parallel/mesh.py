"""shard_map data parallelism: sharded player table, sharded scatter.

Design (SURVEY.md section 7, step 5 — round-2 rework):

Round 1 replicated the player table and had every chip apply the identical
full-batch scatter after an ``all_gather`` of the updates. But the scatter
IS the superstep on this hardware — measured on v5e at B=512: whole-row
gather + all closed-form compute ~35 us, the row scatter ~370 us (XLA
serializes ~72 ns/row regardless of scatter variant; see core/update.py).
Replicating the dominant cost caps an 8-chip pod at ~1.1x one chip. So the
table is now **sharded**:

  * Each chip owns ``rows_per_shard = ceil((P+1)/D)`` player rows,
    **interleaved** (global row r -> shard r % D at local row r // D; the
    table is padded to ``D * rows_per_shard``). Interleaving keeps
    per-shard update counts near-binomial even when player ids cluster.
  * **Prior assembly** (replaces the replicated gather): every chip gathers
    candidate rows for the full flattened batch from its own shard
    (out-of-shard slots clamp and zero via ``where``) and one ``psum``
    over the mesh sums the disjoint contributions — each slot's row comes
    from exactly its owner, bit-identically (x + 0 = x). Cost: one
    ``[B*2*T, 16]`` f32 psum (~330 KB at B=512) riding ICI, plus the same
    ~35 us gather+compute each chip already did.
  * **Compute is replicated** — it is cheap and keeping it identical on
    every chip means no second exchange: every chip holds the full
    ``new_rows`` after :func:`~analyzer_tpu.core.update.rate_gathered`.
  * **The scatter is sharded** — the host-side scheduler already knows
    every superstep's player rows, so :func:`build_routing` precomputes,
    per (superstep, shard), the compacted list of update slots that land in
    that shard (``sel``: flat slot position, ``dst``: local row). Each chip
    scatters only its own ``K ~ valid_slots/D`` rows; padding entries point
    one past the shard (``mode="drop"``). This divides the ~370 us scatter
    by the mesh size.

Scaling model (v5e, B=512, 10 slots/match): t_step(D) ~ 35 us [gather +
replicated compute] + t_psum(D) [~330 KB ring all-reduce, ~5-15 us on ICI]
+ 370 us * K/N / D [sharded scatter, K/N ~ occupancy * (1 + imbalance)].
Single chip ~405 us -> D=8 predicts ~90-100 us, i.e. ~4-4.5x throughput —
a real speedup where round 1 had ~1.1x, with per-chip HBM for the table
also divided by D. MEASURED single-chip constant (round 3, BASELINE.md
"Measured (round 3)"): the sharded runner at D=1 costs ~1.7x the plain
runner on the real chip (1.29 s vs 0.76 s per 500k with precomputed
routing; the D=1 psum/all_gather are pure copies, so this is the
replicated-gather + routing-transfer overhead the model attributes to
t_psum + feed). Breakeven vs one plain chip is therefore ~2 real chips,
and the D=8 prediction stands as a model until real multi-chip hardware
exists to measure on. The round-3 ablation
(experiments/sharded_overhead.py) measured the sharded step's DEVICE
work as free at D=1 — psum assembly and compacted scatter both compile
to the plain path's cost, and the measured ~1.7x single-chip e2e
constant is feed logistics (per-chunk H2D + setup + unshard), not
compute. The replicated candidate gather's real cost (the psum as an
actual ICI collective) appears only at D>1; sharding it via
host-compacted gather routing + reduce_scatter remains the lever to
evaluate once multi-chip hardware exists.

Correctness invariants (tested bit-identical vs the single-device runner on
1/2/4/8 virtual CPU devices, tests/test_parallel.py):
  * a superstep is conflict-free globally, so shard scatters never collide;
  * psum contributions are disjoint (each row has exactly one owner), so
    prior assembly is exact, including NaN never-rated markers (non-owner
    contributions are hard zeros via ``where``, never ``NaN * 0``);
  * non-ratable/masked slots are excluded from routing on the host — the
    reference's AFK/unsupported gates (``rater.py:83-106``) write no state.

Multi-host runs use the same code: ``jax.distributed.initialize()`` +
a global mesh makes the psum ride ICI within a slice and DCN across
slices; the host feed stays sharded by process.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core.state import MatchBatch, PlayerState
from analyzer_tpu.core.update import rate_gathered
from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs import get_registry, get_tracer
from analyzer_tpu.sched.superstep import PackedSchedule

logger = get_logger(__name__)

DATA_AXIS = "data"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    """A 1-D ``data`` mesh over the first ``n_devices`` local devices.
    Raises when fewer devices exist than asked for — silently truncating
    would run at lower parallelism than the caller sized the batch for."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            if len(devices) < n_devices:
                raise ValueError(
                    f"asked for a {n_devices}-device mesh but only "
                    f"{len(devices)} devices are available"
                )
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


@dataclasses.dataclass(frozen=True)
class Routing:
    """Host-precomputed per-(superstep, shard) scatter compaction.

    Ownership is **interleaved**: global player row ``r`` lives in shard
    ``r % D`` at local row ``r // D``. Interleaving makes per-shard update
    counts near-binomial regardless of player-id locality (contiguous
    blocks would let an id-clustered superstep pile its whole batch onto
    one shard, inflating the global capacity ``K`` and with it every
    step's scatter cost).

    sel ``[S, D, K]`` int32: flat slot positions (into the ``B*2*T``
      flattened batch) whose player row lives in shard ``d`` at step ``s``;
      padded with 0 (the padding ``dst`` makes the write a no-op).
    dst ``[S, D, K]`` int32: the slot's player row, shard-local; padding
      entries hold ``rows_per_shard`` (out of bounds -> dropped by the
      ``mode="drop"`` scatter).
    """

    sel: np.ndarray
    dst: np.ndarray
    rows_per_shard: int
    n_shards: int

    @property
    def capacity(self) -> int:
        return self.sel.shape[2]


def build_routing(
    sched: PackedSchedule, n_table_rows: int, n_shards: int
) -> Routing:
    """Routes every written slot (``sched.valid_slots``) to its owner shard.

    Vectorized over the whole schedule: one stable argsort of slot->owner
    per step groups each shard's slots contiguously; ``K`` is the max
    per-(step, shard) count so one static shape serves the whole run.

    This is the EAGER form — it needs the whole ``[S, B, 2, T]`` gather
    tensors and holds ``[S, D, K]`` routing in host memory at once. The
    windowed feed (:class:`ShardedRun` / ``rate_history_sharded`` over a
    ``WindowedSchedule``) calls :func:`_window_routing` per chunk instead
    and never materializes either; use this only to precompute routing for
    repeated runs over the same eager schedule (benchmarks)."""
    s_steps, b = sched.match_idx.shape
    n = b * 2 * sched.player_idx.shape[-1]
    rps = -(-n_table_rows // n_shards)
    idx = sched.player_idx.reshape(s_steps, n).astype(np.int64)
    valid = sched.valid_slots.reshape(s_steps, n)
    sel, dst = _window_routing(idx, valid, n_shards, rps)
    return Routing(sel=sel, dst=dst, rows_per_shard=rps, n_shards=n_shards)


def _window_routing(
    idx_flat: np.ndarray, valid_flat: np.ndarray, n_shards: int, rps: int
) -> tuple[np.ndarray, np.ndarray]:
    """The routing core on flattened ``[W, n]`` window arrays: returns
    (sel, dst) ``[W, D, K]`` int32 at the window's exact capacity
    ``K = max per-(step, shard) valid-slot count`` (>= 1). Padding entries
    hold sel 0 / dst ``rps`` (out of bounds -> dropped by the scatter).

    This is the windowed mesh feed's main host cost (~0.2 s per 1024-step
    window at B=256, D=8 — ~0.4 s per 500k matches); on a pod, device
    time divides by D while this doesn't, so its constant sets the feed's
    scaling headroom. A hand-rolled vectorized counting sort over the
    tiny owner range was tried and MEASURED SLOWER (278 ms vs 208 ms per
    window): numpy's stable integer argsort is already a C radix sort, so
    the D-pass cumsum ranking just multiplies memory traffic."""
    w, n = idx_flat.shape
    owner = np.where(valid_flat, _owner(idx_flat, n_shards), n_shards)

    order = np.argsort(owner, axis=1, kind="stable")
    sorted_owner = np.take_along_axis(owner, order, axis=1)
    flat = (sorted_owner + np.arange(w)[:, None] * (n_shards + 1)).ravel()
    counts = np.bincount(flat, minlength=w * (n_shards + 1)).reshape(
        w, n_shards + 1
    )[:, :n_shards]

    k = max(int(counts.max()) if counts.size else 0, 1)
    start = np.cumsum(counts, axis=1) - counts  # [W, D] exclusive prefix
    pos = start[:, :, None] + np.arange(k)[None, None, :]  # [W, D, K]
    in_range = np.arange(k)[None, None, :] < counts[:, :, None]
    pos = np.minimum(pos, n - 1)
    sel = np.take_along_axis(order, pos.reshape(w, -1), axis=1).reshape(
        w, n_shards, k
    )
    rows = np.take_along_axis(idx_flat, sel.reshape(w, -1), axis=1).reshape(
        w, n_shards, k
    )
    dst = _local_row(rows, n_shards)
    return (
        np.where(in_range, sel, 0).astype(np.int32),
        np.where(in_range, dst, rps).astype(np.int32),
    )


def _owner(row, n_shards):
    """Interleaved ownership, THE layout invariant: global row r lives in
    shard ``r % D`` at local row ``r // D``. Used by the host routing, the
    device-side prior assembly, and the (un)reorder helpers below — change
    all of them together or not at all."""
    return row % n_shards


def _local_row(row, n_shards):
    return row // n_shards


def _to_shard_major(table, n_shards: int, rows_per_shard: int):
    """[D*rps, W] row-major -> shard-major concat ([D, rps, W] flattened):
    shard d's block holds global rows d, d+D, d+2D, ... so that row-sharding
    the result over ``data`` gives each chip exactly its owned rows."""
    width = table.shape[-1]
    return (
        table.reshape(rows_per_shard, n_shards, width)
        .transpose(1, 0, 2)
        .reshape(-1, width)
    )


def _from_shard_major(table, n_shards: int, rows_per_shard: int):
    """Inverse of :func:`_to_shard_major`."""
    width = table.shape[-1]
    return (
        table.reshape(n_shards, rows_per_shard, width)
        .transpose(1, 0, 2)
        .reshape(-1, width)
    )


def _put_global(arr, sharding: NamedSharding):
    """``device_put`` that also works when the mesh spans processes.

    Single-process: plain ``device_put``. Multi-process (after
    ``jax.distributed.initialize``): every process holds the same host
    array (packing is deterministic, so each host computes an identical
    schedule) and materializes ONLY its addressable devices' shards —
    ``make_array_from_callback`` invokes the callback just for local
    shard indices, which is the per-process slice of the feed
    (``multihost.process_slice`` semantics, done per device)."""
    nbytes = getattr(arr, "nbytes", None)
    if nbytes is not None:
        # Host->device transfer accounting: the windowed mesh feed's
        # per-chunk uploads are the feed-logistics constant BASELINE.md's
        # D=1 ablation pinned — the counters make that tax visible per
        # run instead of per-investigation (docs/observability.md).
        reg = get_registry()
        reg.counter("mesh.put_bytes_total").add(int(nbytes))
        reg.counter("mesh.puts_total").add(1)
    if jax.process_count() == 1:
        return jax.device_put(arr, sharding)
    arr = np.asarray(arr)
    return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])


_step_fn_cache: dict = {}


def sharded_step_fn(
    mesh: Mesh, cfg: RatingConfig, rows_per_shard: int, pad_row: int
):
    """Builds (and memoizes — jit cache can't see through fresh closures)
    the jitted, shard_map'd chunk runner over the sharded table.

    Returns ``run(table, pidx, winner, mode, afk, sel, dst) -> table``
    scanning over the leading superstep axis; ``table`` is row-sharded over
    ``data`` and donated, the batch axis is sharded, ``sel``/``dst`` carry
    one ``[K]`` block per shard. The feed is COMPACT, mirroring the
    single-device runner (sched.superstep.compact_device_window): no
    slot_mask (derived here as ``player_idx != pad_row`` — the invariant
    every schedule producer guarantees) and int8 winner/mode_id, widened
    on device. The mask all_gather it replaces was ~15% of the window
    transfer, on the path BASELINE.md's D=1 ablation pinned as pure feed
    logistics.
    """
    key = (
        tuple(d.id for d in mesh.devices.flat), cfg, rows_per_shard, pad_row,
    )
    cached = _step_fn_cache.get(key)
    if cached is not None:
        return cached

    def scan_chunk(table, pidx, winner, mode, afk, sel, dst):
        me = jax.lax.axis_index(DATA_AXIS)
        n_shards = jax.lax.axis_size(DATA_AXIS)

        def step(tbl, xs):
            lp, lw, lmo, la, s_, d_ = xs  # local [B/D, ...] + [1, K]
            gather = lambda x: jax.lax.all_gather(x, DATA_AXIS, axis=0, tiled=True)
            gp = gather(lp)
            batch = MatchBatch(
                player_idx=gp,
                slot_mask=gp != pad_row,
                winner=gather(lw).astype(jnp.int32),
                mode_id=gather(lmo).astype(jnp.int32),
                afk=gather(la),
            )
            # Prior assembly: candidates from this shard, zeros elsewhere;
            # the psum of disjoint contributions reconstructs the global
            # gather exactly (x + 0 = x, and NaN markers pass through the
            # owner's contribution untouched). Ownership is interleaved:
            # global row r -> shard r % D, local row r // D (see Routing).
            flat = batch.player_idx.reshape(-1)
            owned = _owner(flat, n_shards) == me
            loc = _local_row(flat, n_shards)
            cand = tbl[jnp.clip(loc, 0, rows_per_shard - 1)]
            rows = jax.lax.psum(
                jnp.where(owned[:, None], cand, 0.0), DATA_AXIS
            ).reshape(batch.player_idx.shape + (tbl.shape[-1],))

            out = rate_gathered(rows, batch, cfg)  # replicated, bit-identical

            # Sharded scatter: only this shard's K compacted rows; padding
            # entries point one past the shard and are dropped.
            new_flat = out.new_rows.reshape(-1, tbl.shape[-1])
            tbl = tbl.at[d_[0]].set(new_flat[s_[0]], mode="drop")
            return tbl, None

        table, _ = jax.lax.scan(
            step, table, (pidx, winner, mode, afk, sel, dst)
        )
        return table

    tspec = P(DATA_AXIS, None)  # [D*rps, W]: row-sharded table
    bspec = P(None, DATA_AXIS)  # [S, B, ...]: shard the batch axis
    rspec = P(None, DATA_AXIS, None)  # [S, D, K]: one block per shard
    # check_vma=False: the varying-manual-axes checker types all_gather /
    # psum outputs as varying, but the replicated compute is invariant by
    # construction (disjoint psum contributions) — asserted bit-identical
    # vs single-device in tests/test_parallel.py.
    shmapped = jax.shard_map(
        scan_chunk,
        mesh=mesh,
        in_specs=(tspec, bspec, bspec, bspec, bspec, rspec, rspec),
        out_specs=tspec,
        check_vma=False,
    )
    fn = jax.jit(shmapped, donate_argnums=(0,))
    _step_fn_cache[key] = fn
    return fn


class ShardedRun:
    """The device-side half of the sharded re-rate, factored so ANY host
    feed — an eager :class:`PackedSchedule`, a lazy ``WindowedSchedule``
    window loop, or ``rate_stream``'s concurrent assignment — can drive
    the same sharded scan one window at a time with O(window) host memory.

    Holds the padded, shard-major, row-sharded table plus the compiled
    step function; :meth:`dispatch` routes and runs one ``[W, B, ...]``
    window. Routing capacity ``K`` is bucketed (25% headroom, multiple of
    8) so consecutive windows reuse one compiled scan; a window whose
    per-(step, shard) count outgrows the bucket grows it — one recompile,
    logged — and buckets never shrink.
    """

    def __init__(
        self,
        state: PlayerState,
        cfg: RatingConfig,
        mesh: Mesh,
        routing_capacity: int | None = None,
        track_dirty: bool = False,
    ) -> None:
        if (
            state.seed_cfg is not None
            and state.seed_cfg.unknown_player_sigma != cfg.unknown_player_sigma
        ):
            # Same contract as rate_batch (core/update.py) — checked here
            # once because the sharded path assembles rows itself via
            # rate_gathered.
            raise ValueError(
                f"state seeds were built with UNKNOWN_PLAYER_SIGMA="
                f"{state.seed_cfg.unknown_player_sigma}, but the sharded "
                f"rater was called with {cfg.unknown_player_sigma}; rebuild "
                "the state via PlayerState.create(..., cfg=cfg)"
            )
        self.mesh = mesh
        self.cfg = cfg
        self.n_dev = int(mesh.devices.size)
        self.n_rows = state.table.shape[0]
        self.rps = -(-self.n_rows // self.n_dev)
        self._cap = routing_capacity
        self._state = state
        self._step_fn = sharded_step_fn(
            mesh, cfg, self.rps, state.pad_row
        )
        self._batch_sh = NamedSharding(mesh, P(None, DATA_AXIS))
        self._route_sh = NamedSharding(mesh, P(None, DATA_AXIS, None))
        # Per-shard dirty-row accounting for the sharded serve plane:
        # the routing's dst lists already name every local row each
        # shard writes, so a view publish ships exactly those rows —
        # producer (stage) computes, consumer (dispatch) accumulates,
        # publish drains. Off unless a publisher is wired.
        self.track_dirty = track_dirty
        self._dirty: list[list[np.ndarray]] = [
            [] for _ in range(self.n_dev)
        ]

        # Pad the table to D * rps rows, reorder into shard-major
        # (interleaved ownership: global row r -> shard r % D, local row
        # r // D), and shard it. The reorder also guarantees a fresh
        # buffer, so the donated scan never frees the CALLER's state
        # (same guard as sched.runner).
        pad = self.n_dev * self.rps - self.n_rows
        width = state.table.shape[1]
        table = state.table
        if pad:
            table = jnp.concatenate(
                [table, jnp.full((pad, width), jnp.nan, table.dtype)]
            )
        table = _to_shard_major(table, self.n_dev, self.rps)
        self._table = _put_global(table, NamedSharding(mesh, P(DATA_AXIS, None)))

        # Undo the shard-major reorder under jit with a replicated output
        # sharding: the result table is row-sharded across the mesh
        # (possibly across processes on multi-host), where eager
        # reshape/transpose/slice would raise on non-fully-addressable
        # arrays.
        self._unshard = jax.jit(
            lambda t: _from_shard_major(t, self.n_dev, self.rps)[: self.n_rows],
            out_shardings=NamedSharding(mesh, P()),
        )

    def _route_window(
        self, pidx: np.ndarray, mask: np.ndarray, mode_id: np.ndarray,
        afk: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Per-window routing, padded to the capacity bucket.

        Alongside the compaction, the window's written-row list feeds the
        residency reuse accounting shared with the fused kernel's planner
        (``sched.residency.window_reuse_stats``): each row instance beyond
        its first is a scatter a per-shard fused working set would have
        absorbed — the single-chip fused kernel (``core.fused``) already
        does, and the per-shard variant would reuse exactly these
        compacted ``dst`` lists for its plan. Until that kernel exists,
        ``mesh.writebacks_avoidable_total`` quantifies what it is worth
        per run instead of per investigation."""
        from analyzer_tpu.sched.residency import window_reuse_stats

        ratable = (mode_id >= 0) & ~afk
        valid = mask & ratable[:, :, None, None]
        w = pidx.shape[0]
        idx = pidx.reshape(w, -1).astype(np.int64)
        uniq, instances = window_reuse_stats(idx[valid.reshape(w, -1)])
        if instances > uniq:
            get_registry().counter("mesh.writebacks_avoidable_total").add(
                instances - uniq
            )
        sel, dst = _window_routing(
            idx, valid.reshape(w, -1), self.n_dev, self.rps
        )
        k = sel.shape[2]
        if self._cap is None or k > self._cap:
            new_cap = max(8, -(-int(k * 1.25) // 8) * 8)
            if self._cap is not None:
                logger.info(
                    "sharded routing capacity grew %d -> %d (one recompile)",
                    self._cap, new_cap,
                )
            self._cap = max(new_cap, self._cap or 0)
        if k < self._cap:
            pad = np.zeros(sel.shape[:2] + (self._cap - k,), np.int32)
            sel = np.concatenate([sel, pad], axis=2)
            dst = np.concatenate([dst, pad + self.rps], axis=2)
        return sel, dst

    def stage(
        self,
        pidx: np.ndarray,
        mask: np.ndarray,
        winner: np.ndarray,
        mode_id: np.ndarray,
        afk: np.ndarray,
        sel: np.ndarray | None = None,
        dst: np.ndarray | None = None,
    ) -> tuple:
        """The HOST half of :meth:`dispatch`: routes (unless precomputed
        sel/dst are given) and device-commits one window's arrays
        without running it. Touches neither the table nor the step fn,
        so a prefetch thread (``sched.feed``) can stage window k+1 while
        the consumer thread executes window k. ``mask`` is consumed
        host-side (routing) only — the device derives it from
        ``pidx != pad_row``, and winner/mode cross the link as int8
        (the step fn widens them). With ``track_dirty`` the staged
        tuple also carries each shard's written local rows (from the
        same compacted ``dst`` lists the scatter consumes) for the
        serve plane's per-shard patch publish."""
        if sel is None:
            sel, dst = self._route_window(pidx, mask, mode_id, afk)
        dirty = None
        if self.track_dirty:
            dirty = []
            for d in range(self.n_dev):
                rows = np.unique(dst[:, d, :])
                dirty.append(rows[rows < self.rps].astype(np.int64))
        return (
            _put_global(pidx, self._batch_sh),
            _put_global(winner.astype(np.int8), self._batch_sh),
            _put_global(mode_id.astype(np.int8), self._batch_sh),
            _put_global(afk, self._batch_sh),
            _put_global(sel, self._route_sh),
            _put_global(dst, self._route_sh),
            dirty,
        )

    def dispatch_staged(self, staged: tuple) -> None:
        """Runs one staged window (donates and replaces the carried
        table). Consumer-thread only — the donation chain on the table
        is what serializes windows; the dirty accumulation shares that
        ordering, so a publish covers exactly the windows dispatched
        before it."""
        *dev, dirty = staged
        if dirty is not None:
            for d, rows in enumerate(dirty):
                if rows.size:
                    self._dirty[d].append(rows)
        self._table = self._step_fn(self._table, *dev)

    def dispatch(
        self,
        pidx: np.ndarray,
        mask: np.ndarray,
        winner: np.ndarray,
        mode_id: np.ndarray,
        afk: np.ndarray,
        sel: np.ndarray | None = None,
        dst: np.ndarray | None = None,
    ) -> None:
        """Stage + run one window in one call. Async — returns at
        dispatch, so the caller's next window materialization overlaps
        this window's device execution."""
        self.dispatch_staged(
            self.stage(pidx, mask, winner, mode_id, afk, sel, dst)
        )

    def call_hook(self, on_chunk, next_step: int) -> None:
        """Invokes ``on_chunk(snapshot, next_step)`` with a ZERO-ARG THUNK
        producing the fully-assembled (unsharded, row-major) PlayerState.
        Evaluating it is a cross-process collective, so a multi-host hook
        must call it on every process or on none (make the decision a
        pure function of ``next_step``); skipped chunks pay nothing. The
        thunk must be consumed INSIDE the hook: the captured buffer is
        donated to the next dispatch, so deferred evaluation would be a
        use-after-donate — it raises loudly instead."""
        live = [True]

        def snapshot(_t=self._table, _live=live):
            if not _live[0]:
                raise RuntimeError(
                    "snapshot thunk evaluated after on_chunk returned; "
                    "the table buffer it captures is donated to the "
                    "next chunk — consume it inside the hook"
                )
            return dataclasses.replace(self._state, table=self._unshard(_t))

        on_chunk(snapshot, next_step)
        live[0] = False

    # -- sharded serve-plane publish --------------------------------------
    def _shard_blocks(self) -> list[np.ndarray]:
        """Each shard's ``[rps, W]`` block fetched D2H INDEPENDENTLY
        (``addressable_shards`` — never a cross-shard gather). Block
        ``d``'s local row ``j`` is global row ``j*D + d``: the
        shard-major layout IS the serve plane's interleaved local
        order, so the blocks feed ``ShardedViewPublisher`` verbatim."""
        shards = sorted(
            self._table.addressable_shards,
            key=lambda s: (s.index[0].start or 0),
        )
        return [np.asarray(s.data) for s in shards]

    def maybe_publish_views(self, publisher) -> bool:
        """Throttled :meth:`publish_views` (the chunk-boundary hook)."""
        if not publisher.due():
            return False
        self.publish_views(publisher)
        return True

    def publish_views(self, publisher) -> None:
        """Publishes one version-consistent per-shard view set: each
        shard's block crosses D2H on its own, and only the local rows
        written since the last publish (the accumulated routing ``dst``
        lists) ride the per-shard H2D patch path back up into the
        serving tables. ``publisher`` is a
        :class:`~analyzer_tpu.serve.view.ShardedViewPublisher` with
        ``n_shards == mesh size`` (validated by the runner wiring)."""
        blocks = self._shard_blocks()
        n_players = self.n_rows - 1
        patches = []
        for d in range(self.n_dev):
            if self._dirty[d]:
                rows_idx = np.unique(np.concatenate(self._dirty[d]))
            else:
                rows_idx = np.empty(0, np.int64)
            patches.append((rows_idx, blocks[d][rows_idx]))
            self._dirty[d] = []
        publisher.publish_shard_patches(
            patches, n_players, lambda: blocks
        )

    def finish(self) -> PlayerState:
        """Assembles and returns the final row-major state."""
        return dataclasses.replace(
            self._state, table=self._unshard(self._table)
        )


def rate_history_sharded(
    state: PlayerState,
    sched,
    cfg: RatingConfig,
    mesh: Mesh | None = None,
    steps_per_chunk: int = 1024,
    start_step: int = 0,
    stop_after: int | None = None,
    on_chunk=None,
    routing: Routing | None = None,
    routing_capacity: int | None = None,
    prefetch_depth: int | None = None,
    view_publisher=None,
    fabric_directory=None,
) -> PlayerState:
    """Full-history re-rate, data-parallel over the mesh. Returns final state.

    ``sched`` may be an eager :class:`PackedSchedule` or a lazy
    ``WindowedSchedule`` — with the latter, both the gather tensors AND
    the scatter routing are built per chunk inside the feed loop (O(window)
    host memory; the round-2 eager pack + whole-schedule routing are gone).
    ``sched.batch_size`` must be divisible by the mesh size (pack with
    ``batch_size = k * n_devices``). ``start_step``/``stop_after``/
    ``on_chunk`` mirror ``sched.rate_history``'s checkpoint-resume surface;
    the hook receives a snapshot THUNK — see :meth:`ShardedRun.call_hook`
    for the multi-host discipline. ``routing`` lets callers reuse a
    precomputed :func:`build_routing` across calls (benchmarks, resumed
    runs on the same eager schedule); it is validated against the mesh and
    table shape. ``routing_capacity`` presets the per-window routing
    bucket (K) so a resumed run compiles the same shapes up front.

    The feed rides the bounded prefetcher (``sched.feed``,
    ``prefetch_depth`` default 2): window materialization, routing, and
    the sharded ``device_put``s run on a producer thread up to depth
    windows ahead of the in-flight sharded step — the feed-logistics
    constant BASELINE.md's D=1 ablation pinned now overlaps device time
    instead of preceding it. Chunk order, hook boundaries, and results
    are depth-invariant.

    ``view_publisher`` wires the sharded SERVE plane (the read half of
    ROADMAP item 2): a
    :class:`~analyzer_tpu.serve.view.ShardedViewPublisher` whose
    ``n_shards`` equals the mesh size gets throttled per-shard view
    publishes at chunk boundaries — each shard's dirty rows riding its
    own patch path, one monotone version across shards — plus an
    unthrottled final publish. A plain ``ViewPublisher`` gets only the
    final assembled table (a mid-run cross-shard gather would serialize
    the feed overlap).

    On a multi-process mesh each process only sees its own shards'
    blocks, so a raw sharded publisher would tear the view. Pass
    ``fabric_directory`` (a :class:`~analyzer_tpu.fabric.directory.
    FabricDirectory` whose topology matches the publisher's shard
    count) and this runner wraps the publisher in a
    :class:`~analyzer_tpu.fabric.publish.FabricShardPublisher`: each
    process publishes ONLY the shards it owns under its own monotone
    version, recorded in the directory so fabric readers route around
    staleness (docs/fabric.md).
    """
    mesh = mesh or make_mesh()
    n_dev = mesh.devices.size
    if sched.batch_size % n_dev:
        raise ValueError(
            f"batch_size {sched.batch_size} not divisible by mesh size {n_dev}"
        )
    n_rows = state.table.shape[0]
    # The sharded step derives slot_mask on device as player_idx !=
    # state.pad_row (the compact feed). A schedule packed against a
    # DIFFERENT pad row would mark its padding slots as real players —
    # phantom pad-row teammates silently corrupting the update. Fail
    # loudly instead, like the single-device runner's hand-built-schedule
    # guard (superstep.PackedSchedule.device_arrays).
    if sched.pad_row != state.pad_row:
        raise ValueError(
            f"schedule packed with pad_row={sched.pad_row} but the state "
            f"table's pad row is {state.pad_row}; repack the schedule with "
            "pad_row=state.pad_row"
        )
    check = getattr(sched, "check_compact_invariant", None)
    if check is not None:  # hand-built eager schedules verify; see there
        check()
    if routing is not None and (
        routing.n_shards != n_dev
        or routing.rows_per_shard * n_dev < n_rows
        or routing.sel.shape[0] != sched.n_steps
    ):
        # A routing from a different packing of the same stream can match
        # on shards/rows and still scatter the wrong slots — bind it to
        # this schedule's step count too.
        raise ValueError(
            f"routing was built for {routing.n_shards} shards x "
            f"{routing.rows_per_shard} rows x {routing.sel.shape[0]} steps; "
            f"mesh has {n_dev} devices, the table {n_rows} rows, and the "
            f"schedule {sched.n_steps} steps"
        )

    from analyzer_tpu.sched.feed import DEFAULT_DEPTH, Prefetcher

    sharded_publisher = view_publisher is not None and hasattr(
        view_publisher, "publish_shard_patches"
    )
    if sharded_publisher:
        if fabric_directory is not None:
            from analyzer_tpu.fabric.publish import FabricShardPublisher

            # Each process publishes only its owned shards' patches
            # under its own monotone version; the directory carries the
            # fleet's (host, shards, version) vector for routed reads.
            view_publisher = FabricShardPublisher(
                fabric_directory, jax.process_index(), view_publisher
            )
        elif jax.process_count() != 1:
            raise ValueError(
                "per-shard view publishing on a multi-process mesh "
                "needs a fabric directory (each process only sees its "
                "own shards' blocks — a raw publisher would tear the "
                "view); pass fabric_directory= to route owned shards "
                "through the fabric protocol, or bring the serve tier "
                "up as its own fleet with `cli fabric`"
            )
        if view_publisher.n_shards != n_dev:
            raise ValueError(
                f"view publisher has {view_publisher.n_shards} shards "
                f"but the mesh has {n_dev} devices; build the "
                "ShardedViewPublisher with n_shards == mesh size"
            )

    run = ShardedRun(
        state, cfg, mesh, routing_capacity=routing_capacity,
        track_dirty=sharded_publisher,
    )
    n_steps = sched.n_steps if stop_after is None else min(stop_after, sched.n_steps)
    tracer = get_tracer()

    def produce(put) -> None:
        for start in range(start_step, n_steps, steps_per_chunk):
            stop = min(start + steps_per_chunk, n_steps)
            with tracer.span("feed.materialize", cat="mesh", start=start):
                pidx, mask, winner, mode_id, afk = sched.host_window(
                    start, stop
                )
            with tracer.span("feed.transfer", cat="mesh", start=start):
                staged = run.stage(
                    pidx, mask, winner, mode_id, afk,
                    sel=routing.sel[start:stop] if routing is not None else None,
                    dst=routing.dst[start:stop] if routing is not None else None,
                )
            put((stop, staged))

    with Prefetcher(
        produce, depth=prefetch_depth or DEFAULT_DEPTH, name="mesh-feed"
    ) as pf:
        for stop, staged in pf:
            run.dispatch_staged(staged)
            del staged
            if sharded_publisher:
                run.maybe_publish_views(view_publisher)
            if on_chunk is not None:
                run.call_hook(on_chunk, stop)
    if sharded_publisher:
        run.publish_views(view_publisher)  # final per-shard, unthrottled
    final = run.finish()
    if view_publisher is not None and not sharded_publisher:
        view_publisher.publish_state(final)  # final table, unthrottled
    return final
