"""Multi-host (multi-slice) execution: the DCN story.

The reference scales across machines by pointing more worker processes at
one RabbitMQ (SURVEY.md section 2.5) — no inter-worker communication at
all, consistency left to MySQL races. The TPU-native equivalent is a
single global computation over all hosts' chips:

  * ``jax.distributed.initialize()`` (coordinator address + process id
    from the environment) joins every host into one runtime;
  * the SAME mesh/shard_map code in :mod:`analyzer_tpu.parallel.mesh` then
    spans all chips — ``jax.devices()`` is global, ``all_gather`` of the
    posterior rows rides ICI within a slice and DCN across slices (it is
    batch-shaped, KBs per superstep, so DCN latency hides under compute);
  * each process feeds only its own shard of the packed schedule
    (``process_slice`` below): device_put of a globally-sharded array from
    per-host shards is how JAX expects multi-host input to arrive.

Driven end-to-end by ``python -m analyzer_tpu.cli rate --mesh 0`` (see
``cli._rate_mesh``: same command on every host with the jax.distributed
env set; each process feeds only its addressable shards via
``parallel.mesh._put_global``), and exercised in CI by a REAL 2-process
CPU cluster — ``tests/test_multihost.py`` forms a 2x2-device global mesh
over Gloo and requires the sharded re-rate to be bit-identical to a
single-device run, psum crossing the process boundary the way DCN
traffic would.
"""

from __future__ import annotations

import os

import jax


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> bool:
    """Joins the global runtime when multi-host env/args are present.

    Returns True if distributed mode is active. No-ops (returns False) for
    single-host runs, so callers can unconditionally call it first.
    Environment fallbacks: COORDINATOR_ADDRESS, NUM_PROCESSES, PROCESS_ID
    (the standard jax.distributed knobs).
    """
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    if not coordinator_address:
        return False
    kwargs = {"coordinator_address": coordinator_address}
    num_processes = num_processes or int(os.environ.get("NUM_PROCESSES", 0)) or None
    process_id = (
        process_id
        if process_id is not None
        else (int(os.environ["PROCESS_ID"]) if "PROCESS_ID" in os.environ else None)
    )
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
    return True


def assert_processes_agree(label: str, *arrays) -> None:
    """Verifies every process holds identical host-side inputs (digests
    compared via a cross-process collective). No-op single-process.

    The multi-host feed contract assumes each host computed the SAME
    stream/state (deterministic packing from identical files); a stale
    NFS copy of a checkpoint on one host would otherwise materialize a
    globally inconsistent sharded table and produce silently wrong
    ratings. Digest-compare is cheap (20 bytes over DCN) regardless of
    array sizes."""
    if jax.process_count() == 1:
        return
    import hashlib

    import numpy as np
    from jax.experimental import multihost_utils

    h = hashlib.sha1()
    for a in arrays:
        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())
    digest = np.frombuffer(h.digest(), dtype=np.uint8).astype(np.int32)
    try:
        multihost_utils.assert_equal(
            digest, f"{label}: processes disagree on host inputs"
        )
    except AssertionError as e:
        raise RuntimeError(
            f"{label}: host inputs differ across processes (stale checkpoint "
            "copy / divergent stream file?) — aborting before feeding an "
            "inconsistent sharded table"
        ) from e


def process_slice(n: int) -> slice:
    """This process's contiguous shard of an ``n``-item host-side feed
    (schedule chunks, CSV rows): process i of P gets [i*n/P, (i+1)*n/P)."""
    p = jax.process_count()
    i = jax.process_index()
    lo = i * n // p
    hi = (i + 1) * n // p
    return slice(lo, hi)
