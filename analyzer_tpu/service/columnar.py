"""Columnar service lane: batch encode + write-back without object graphs.

The object lane (``SqlStore.load_batch`` -> ``EncodedBatch`` ->
``write_back`` -> ``commit``) round-trips every batch through ~11k
SimpleNamespace objects and ~100k dynamic attribute accesses. On the
1-core reference host every one of those python operations serializes
with everything else (the pipelined writer thread shares the GIL), and
profiling (round 5) put the object build + write-back at over half of the
service loop's per-batch host time. This lane keeps the SQL queries and
the SEMANTICS — gating rules, poison attribution, the reference's write
set (``rater.py:83-106,140-169``) — and replaces the object plumbing with
numpy over the raw rows (``SqlStore.load_batch_raw``).

Semantics parity is the contract, pinned by differential tests
(``tests/test_columnar.py``): for any batch, the final DATABASE STATE
after this lane equals the object lane's, and every poison/gate decision
(PoisonMatchError / PoisonTierError api_id sets, AFK gating, unsupported
skips) is identical. One DELIBERATE divergence, document-level: the
write plan updates only TOUCHED rows/columns, where the object lane
rewrites every loaded column with its (possibly just-loaded) value.
Final values agree whenever loads see current state — always, for the
sequential loop — but under pipelining the object lane's rewrite of a
stale snapshot value could regress a player row committed by an
in-flight predecessor batch (its chain patch fixes device priors, not
loaded python attributes). Touched-only writes are also what the
reference's ORM flush does: automap never UPDATEs unmodified attributes.

This lane is also the SEMANTICS CONTRACT for the wire-speed columnar
ingest decoder (``io/ingest.py``, docs/ingest.md): the decoder's
windowed output is bit-identical to the codec path's arrays, so every
gate this module applies downstream — AFK/validity, unsupported-mode
skips, the poison attribution above, the write set — is identical
whichever path the bytes arrived through (pinned by the differential
tests in ``tests/test_ingest.py``).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core import constants
from analyzer_tpu.core.seeding import trueskill_seed_host
from analyzer_tpu.core.state import (
    COL_SEED_MU,
    COL_SEED_SIGMA,
    MAX_TEAM_SIZE,
    MU_LO,
    SIGMA_LO,
    TABLE_WIDTH,
    PlayerState,
)
from analyzer_tpu.sched.superstep import MatchStream
from analyzer_tpu.service.encode import (
    PoisonMatchError,
    PoisonTierError,
    row_bucket,
)


def _first_occurrence_rank(values: np.ndarray):
    """(rank_of_each, n_unique): ranks unique values by FIRST appearance
    order (the object lane's dict-insertion numbering)."""
    _, first_idx, inv = np.unique(values, return_index=True, return_inverse=True)
    order = np.argsort(first_idx, kind="stable")
    rank = np.empty(order.size, np.int64)
    rank[order] = np.arange(order.size)
    return rank[inv], order.size


def _index_of(haystack: np.ndarray, needles: np.ndarray):
    """Position of each needle in ``haystack`` (unique values), ok mask
    for misses."""
    if haystack.size == 0 or needles.size == 0:
        return (np.zeros(needles.shape, np.int64),
                np.zeros(needles.shape, bool))
    order = np.argsort(haystack, kind="stable")
    sh = haystack[order]
    pos = np.searchsorted(sh, needles)
    pos = np.minimum(pos, sh.size - 1)
    got = order[pos]
    return got, sh[pos] == needles


def _cumcount(keys: np.ndarray) -> np.ndarray:
    """Occurrence index within each key group, arrival-order stable."""
    if keys.size == 0:
        return np.zeros(0, np.int64)
    order = np.argsort(keys, kind="stable")
    sk = keys[order]
    first = np.r_[True, sk[1:] != sk[:-1]]
    start = np.maximum.accumulate(np.where(first, np.arange(sk.size), 0))
    out = np.empty(sk.size, np.int64)
    out[order] = np.arange(sk.size) - start
    return out


def _float_col(col) -> np.ndarray:
    """column (object numbers/None, or an already-typed array) ->
    float64 with NaN for NULL."""
    col = np.asarray(col)
    if col.dtype != object:
        return col.astype(np.float64)
    out = np.empty(col.shape[0], np.float64)
    mask = np.array([v is None for v in col], bool)
    out[mask] = np.nan
    if (~mask).any():
        out[~mask] = col[~mask].astype(np.float64)
    return out


def _as_str(arr: np.ndarray) -> np.ndarray:
    """S-dtype (native scanner) -> unicode; object/U passes through."""
    if arr.dtype.kind == "S":
        return np.char.decode(arr, "utf-8")
    return arr


def _bool_col(col) -> np.ndarray:
    col = np.asarray(col)
    if col.dtype == object:
        return np.array([bool(v) for v in col], bool)
    return col != 0


def _normalize(raw: dict) -> dict:
    """Row-bundle form (``load_batch_raw`` / ``synthetic_raw_batch``) ->
    the array form ``load_batch_native`` produces, so the encoder has
    ONE data layout. Native S-dtype id columns stay S (joins run on
    fixed-width bytes); the encoder decodes only outward-facing ids."""
    if "match" in raw:
        return raw
    def cols(rows, names):
        if not rows:
            return {n: np.empty(0, object) for n in names}
        t = list(zip(*rows))
        return {n: np.array(t[i], object) for i, n in enumerate(names)}

    return {
        "match": cols(raw["match_rows"], ["api_id", "game_mode", "created_at"]),
        "roster": cols(raw["roster_rows"], ["api_id", "match_api_id", "winner"]),
        "participant": cols(
            raw["part_rows"],
            ["api_id", "match_api_id", "roster_api_id", "player_api_id",
             "skill_tier", "went_afk"],
        ),
        "player": cols(raw["player_rows"], raw["player_cols"]),
        "player_cols": raw["player_cols"],
        "items": cols(raw["items_rows"], raw["items_cols"]),
        "schema_rating_cols": raw["schema_rating_cols"],
        "schema_columns": raw["schema_columns"],
    }


class ColumnarBatch:
    """Array-lane counterpart of :class:`EncodedBatch`, built from
    ``SqlStore.load_batch_raw`` rows. Exposes the same downstream
    surface: ``state``, ``stream``, ``row_of``, ``matches`` (api_ids —
    ``len`` and truthiness match the object lane's list of match
    objects), plus :meth:`write_plan` replacing write_back + commit."""

    def __init__(self, raw: dict, cfg: RatingConfig, bucket_rows: bool = False):
        self.cfg = cfg
        raw = _normalize(raw)
        mid = np.asarray(raw["match"]["api_id"])
        n = int(mid.shape[0])
        self.api_ids: list[str] = list(_as_str(mid))
        self.matches = self.api_ids  # len()/truthiness parity with EncodedBatch
        self.n_matches = n
        self._schema_rating = raw["schema_rating_cols"]
        self._schema_cols = raw["schema_columns"]

        gm = np.asarray(raw["match"]["game_mode"])
        mode = np.full(n, constants.UNSUPPORTED_MODE_ID, np.int32)
        for name, mval in constants.MODE_TO_ID.items():
            key = name.encode() if gm.dtype.kind == "S" else name
            mode[gm == key] = mval

        # -- rosters: arrival order defines team 0/1 ----------------------
        r_id = np.asarray(raw["roster"]["api_id"])
        r_mid = np.asarray(raw["roster"]["match_api_id"])
        r_win = _bool_col(raw["roster"]["winner"])
        r_match, ok = _index_of(mid, r_mid)
        if not ok.all():  # the object lane's by_match[...] KeyError
            raise KeyError(r_mid[~ok][0])
        r_team = _cumcount(r_match)
        roster_count = np.bincount(r_match, minlength=n)
        bad = roster_count != 2  # rater.py:91-93 validity gate

        poison: dict[str, str] = {}
        wflag = np.zeros((n, 2), bool)
        in_team = r_team < 2
        wflag[r_match[in_team], r_team[in_team]] = r_win[in_team]
        tie = ~bad & (wflag[:, 0] == wflag[:, 1])
        for i in np.flatnonzero(tie):
            # Message format matches EncodedBatch (a python bool list).
            flags = [bool(wflag[i, 0]), bool(wflag[i, 1])]
            poison[self.api_ids[i]] = (
                f"rosters must have exactly one winner, got winner "
                f"flags {flags}"
            )
        # The object lane leaves winner at its zero default for bad/tie
        # matches (they never reach the assignment).
        winner = np.where(~bad & ~tie & ~wflag[:, 0], 1, 0).astype(np.int32)

        # -- participants -------------------------------------------------
        p_id = np.asarray(raw["participant"]["api_id"])
        k = int(p_id.shape[0])
        p_id_str = _as_str(p_id)
        p_mid = np.asarray(raw["participant"]["match_api_id"])
        p_rid = np.asarray(raw["participant"]["roster_api_id"])
        p_pid = np.asarray(raw["participant"]["player_api_id"])
        p_afk = raw["participant"]["went_afk"]
        p_match, ok = _index_of(mid, p_mid)
        if not ok.all():
            raise KeyError(_as_str(p_mid[~ok])[0])

        # -- players: encode rows by first appearance in (match, arrival)
        # order — the object lane's dict-insertion numbering over
        # `for m in matches: for part in m.participants`.
        enc_order = np.argsort(p_match, kind="stable")
        player_cols = raw["player_cols"]
        pl = raw["player"]
        pl_id = np.asarray(pl["api_id"])
        pl_id_str = _as_str(pl_id)
        # part player -> player-table row; a dangling player id raises
        # KeyError like the object lane's players[player_api_id].
        p_prow, ok = _index_of(pl_id, p_pid)
        if not ok.all():
            raise KeyError(_as_str(p_pid[~ok])[0])
        row_of_part = np.empty(k, np.int64)
        ranks, p_count = _first_occurrence_rank(p_prow[enc_order])
        row_of_part[enc_order] = ranks
        self.n_players = p = p_count
        # player-table arrival row -> encode row
        arrival_to_enc = np.full(pl_id.size, -1, np.int64)
        arrival_to_enc[p_prow[enc_order]] = ranks  # last write wins; all equal per row
        self.row_of = {
            pid: int(arrival_to_enc[j])
            for j, pid in enumerate(pl_id_str)
            if arrival_to_enc[j] >= 0
        }
        self._player_ids_by_row = np.empty(p, object)
        for j, pid in enumerate(pl_id_str):
            if arrival_to_enc[j] >= 0:
                self._player_ids_by_row[arrival_to_enc[j]] = pid

        alloc = row_bucket(p) if bucket_rows else p

        # -- state table from player columns ------------------------------
        table = np.full((alloc + 1, TABLE_WIDTH), np.nan, np.float32)
        rr = np.full((alloc + 1,), np.nan, np.float32)
        rb = np.full((alloc + 1,), np.nan, np.float32)
        ti = np.zeros((alloc + 1,), np.int32)
        col_at = {c: j for j, c in enumerate(player_cols)}
        enc_of = arrival_to_enc  # alias
        present = enc_of >= 0
        rows_enc = enc_of[present]
        from analyzer_tpu.service.encode import _RATING_ATTRS

        for c, mu_col, sg_col in _RATING_ATTRS:
            if mu_col not in col_at:
                continue
            mu = _float_col(pl[mu_col])
            has_mu = ~np.isnan(mu)
            if has_mu.any():
                if sg_col in col_at:
                    sg = _float_col(pl[sg_col])
                else:
                    sg = np.full(mu.shape, np.nan)
                if (has_mu & np.isnan(sg)).any():
                    # The object lane's float(None) on a mu without its
                    # sigma — malformed data, unattributable.
                    raise TypeError(
                        f"player "
                        f"{pl_id_str[has_mu & np.isnan(sg)][0]!r} has "
                        f"{mu_col} but a NULL/absent {sg_col}"
                    )
                sel = present & has_mu
                table[enc_of[sel], MU_LO + c] = mu[sel].astype(np.float32)
                # Sigma only ever lands next to its mu — the object lane
                # never writes sigma without mu (rows with NULL mu stay
                # NaN in both columns even when sigma has a value).
                table[enc_of[sel], SIGMA_LO + c] = sg[sel].astype(np.float32)
        if "rank_points_ranked" in col_at:
            rr[rows_enc] = _float_col(
                pl["rank_points_ranked"]
            )[present].astype(np.float32)
        if "rank_points_blitz" in col_at:
            rb[rows_enc] = _float_col(
                pl["rank_points_blitz"]
            )[present].astype(np.float32)
        bad_tier: dict[int, object] = {}
        if "skill_tier" in col_at:
            tier_raw = np.asarray(pl["skill_tier"])
            tier_f = _float_col(tier_raw)
            obj_form = tier_raw.dtype == object
            for j in np.flatnonzero(present & ~np.isnan(tier_f)):
                tv = tier_f[j]
                r = int(enc_of[j])
                if not (constants.MIN_SKILL_TIER <= tv <= constants.MAX_SKILL_TIER):
                    # Keep the raw value for the poison message (the
                    # object lane formats what the DB held).
                    bad_tier[r] = (
                        tier_raw[j] if obj_form
                        else (int(tv) if float(tv).is_integer() else tv)
                    )
                    ti[r] = int(min(max(tv, constants.MIN_SKILL_TIER),
                                    constants.MAX_SKILL_TIER))
                else:
                    ti[r] = int(tv)
        seed_mu, seed_sigma = trueskill_seed_host(rr, rb, ti, cfg)
        table[:, COL_SEED_MU] = seed_mu
        table[:, COL_SEED_SIGMA] = seed_sigma
        self.state = PlayerState(
            table=jnp.asarray(table),
            rank_points_ranked=jnp.asarray(rr),
            rank_points_blitz=jnp.asarray(rb),
            skill_tier=jnp.asarray(ti),
            seed_cfg=cfg,
        )

        # -- slotting: participant arrival order within its ROSTER --------
        p_ros, ros_ok = _index_of(r_id, p_rid)
        slot = _cumcount(np.where(ros_ok, p_ros, -1))
        # slot team/match come from the ROSTER's attachment (the object
        # lane slots through roster.participants).
        s_match = np.where(ros_ok, r_match[np.clip(p_ros, 0, None)], -1)
        s_team = np.where(ros_ok, r_team[np.clip(p_ros, 0, None)], -1)
        slottable = (
            ros_ok
            & (s_match >= 0)
            & ~bad[np.clip(s_match, 0, None)]
            & (s_team < 2)
        )
        # Oversize team -> poison that roster's match, void its slots
        # (EncodedBatch: idx[i] = -1 and the raise below gates any use).
        over = slottable & (slot >= MAX_TEAM_SIZE)
        for j in np.flatnonzero(over):
            i = int(s_match[j])
            api = self.api_ids[i]
            if api not in poison:
                team_len = int(
                    (slottable & (s_match == i) & (s_team == s_team[j])).sum()
                )
                poison[api] = (
                    f"team of {team_len} exceeds max team size "
                    f"{MAX_TEAM_SIZE}"
                )
        over_match = np.zeros(n, bool)
        over_match[s_match[over]] = True
        tie_or_over = tie | over_match
        slottable &= ~tie_or_over[np.clip(s_match, 0, None)]

        idx = np.full((n, 2, MAX_TEAM_SIZE), -1, np.int32)
        sj = np.flatnonzero(slottable)
        idx[s_match[sj], s_team[sj], slot[sj]] = row_of_part[sj]

        # -- AFK / validity gate ------------------------------------------
        afk = np.zeros(n, bool)
        p_afk_arr = np.asarray(p_afk)
        if p_afk_arr.dtype == object:
            went = np.array([v == 1 for v in p_afk_arr], bool)
        else:
            went = p_afk_arr == 1
        afk[p_match[went]] = True
        afk |= bad

        # -- items: first row per participant -----------------------------
        it_id = np.asarray(raw["items"]["api_id"])
        it_pid = np.asarray(raw["items"]["participant_api_id"])
        # first arrival per participant = the object lane's
        # participant_items[0]
        it_part, it_ok = _index_of(p_id, it_pid)
        first_seen: dict[int, int] = {}
        for j in np.flatnonzero(it_ok):
            tgt = int(it_part[j])
            if tgt not in first_seen:
                first_seen[tgt] = j
        has_items = np.zeros(k, bool)
        item0_of_part = np.full(k, -1, np.int64)
        for tgt, j in first_seen.items():
            has_items[tgt] = True
            item0_of_part[tgt] = j
        # Missing-items poison for supported-mode matches (write-back
        # target check, rater.py:104,169) — first offender per match,
        # iterating parts in the object lane's m.participants order.
        supported = mode != constants.UNSUPPORTED_MODE_ID
        need = supported[p_match] & ~has_items
        for j in enc_order[need[enc_order]]:
            api = self.api_ids[int(p_match[j])]
            if api in poison:
                continue
            poison[api] = (
                f"participant {str(p_id_str[j])!r} has no "
                "participant_items row (write-back target, "
                "rater.py:104,169)"
            )
        if poison:
            raise PoisonMatchError(
                tuple(poison),
                "; ".join(f"match {a}: {m}" for a, m in poison.items()),
            )

        self.stream = MatchStream(
            player_idx=idx, winner=winner, mode_id=mode, afk=afk
        )

        # -- reference-faithful out-of-table tier gate --------------------
        if bad_tier:
            ratable = (mode >= 0) & ~afk
            used = np.unique(idx[ratable])
            used = used[used >= 0]
            hit_any = np.zeros(n, bool)
            reasons: list[str] = []
            for r in used:
                r = int(r)
                if r not in bad_tier:
                    continue
                no_shared = np.isnan(table[r, MU_LO])
                no_points = (np.isnan(rr[r]) or rr[r] == 0) and (
                    np.isnan(rb[r]) or rb[r] == 0
                )
                if no_shared and no_points:
                    hit_any |= ratable & (idx == r).any(axis=(1, 2))
                    reasons.append(
                        f"player {self._player_ids_by_row[r]}: skill_tier "
                        f"{bad_tier[r]} outside [{constants.MIN_SKILL_TIER}, "
                        f"{constants.MAX_SKILL_TIER}] and the seed would be "
                        "consulted (no shared rating, no rank points)"
                    )
            if reasons:
                raise PoisonTierError(
                    tuple(self.api_ids[i] for i in np.flatnonzero(hit_any)),
                    "; ".join(reasons),
                )

        # -- write-plan precomputation ------------------------------------
        self._p_api = p_id_str
        self._p_match = p_match
        self._row_of_part = row_of_part
        self._slottable = slottable
        self._s_team = s_team
        self._slot = slot
        it_id_str = _as_str(it_id)
        self._item0_api = np.array(
            [it_id_str[item0_of_part[j]] if item0_of_part[j] >= 0 else None
             for j in range(k)],
            dtype=object,
        )

    # -- write-back ------------------------------------------------------
    def write_plan(self, outs) -> list:
        """The reference's write set (``rater.py:140-169``) as
        ``[(table, cols, key, rows), ...]`` for
        :meth:`SqlStore.commit_columnar`, touched rows/columns only. See
        the module docstring for the value-parity argument."""
        n = self.n_matches
        mode = np.asarray(self.stream.mode_id)
        updated = np.asarray(outs.updated, bool)
        supported = mode != constants.UNSUPPORTED_MODE_ID
        rated = supported & updated
        gated = supported & ~updated

        plan: list = []
        sc = self._schema_cols

        # match.trueskill_quality: posterior | int 0 (gate) | NULL
        # (unsupported — the object lane loads quality as None and
        # rewrites it).
        if "trueskill_quality" in sc["match"]:
            q = np.asarray(outs.quality, np.float64)
            rows = []
            for i in range(n):
                if rated[i]:
                    rows.append((float(q[i]), self.api_ids[i]))
                elif gated[i]:
                    rows.append((0, self.api_ids[i]))
                else:
                    rows.append((None, self.api_ids[i]))
            plan.append(("match", ["trueskill_quality"], "api_id", rows))

        # participants: slotted parts of rated matches get posteriors;
        # every other part of a batch match gets NULLs (the object lane
        # writes their loaded Nones).
        sl = self._slottable & rated[self._p_match]
        i_ = self._p_match[sl]
        t_ = self._s_team[sl]
        s_ = self._slot[sl]
        sh_mu = np.asarray(outs.shared_mu, np.float64)[i_, t_, s_]
        sh_sg = np.asarray(outs.shared_sigma, np.float64)[i_, t_, s_]
        dl = np.asarray(outs.delta, np.float64)[i_, t_, s_]
        part_cols = [
            c for c in ("trueskill_mu", "trueskill_sigma", "trueskill_delta")
            if c in sc["participant"]
        ]
        if part_cols == ["trueskill_mu", "trueskill_sigma", "trueskill_delta"]:
            rows = [
                (float(m), float(s), float(d), a)
                for m, s, d, a in zip(sh_mu, sh_sg, dl, self._p_api[sl])
            ]
            rows += [(None, None, None, a) for a in self._p_api[~sl]]
            plan.append(("participant", part_cols, "api_id", rows))
        elif part_cols:  # partial schema: positional subsets
            vals = {
                "trueskill_mu": sh_mu, "trueskill_sigma": sh_sg,
                "trueskill_delta": dl,
            }
            picked = [vals[c] for c in part_cols]
            rows = [
                tuple(float(v[j]) for v in picked) + (a,)
                for j, a in enumerate(self._p_api[sl])
            ]
            rows += [
                (None,) * len(part_cols) + (a,) for a in self._p_api[~sl]
            ]
            plan.append(("participant", part_cols, "api_id", rows))

        # players: per encode row, the LAST slotted-rated appearance sets
        # shared mu/sigma; the last appearance per mode sets that mode's
        # pair. Grouped by touched-column bitmask -> one executemany per
        # distinct column set.
        mode_col_idx = mode[i_] + 1  # RATING_COLUMNS position per write
        q_mu = np.asarray(outs.mode_mu, np.float64)[i_, t_, s_]
        q_sg = np.asarray(outs.mode_sigma, np.float64)[i_, t_, s_]
        prow = self._row_of_part[sl]
        pl_schema = set(self._schema_rating["player"])
        if prow.size:
            p = self.n_players
            # last overall appearance per row (writes ran in (i, t, s)
            # order in the object lane; arrays here are already in part
            # arrival order — re-sort by the write key to be exact)
            wkey = (i_ * 2 + t_) * MAX_TEAM_SIZE + s_
            order = np.argsort(wkey, kind="stable")

            def last_per(key_arr, order):
                rev = order[::-1]
                uniq, first_rev = np.unique(key_arr[rev], return_index=True)
                return uniq, rev[first_rev]

            rows_touched, last_j = last_per(prow, order)
            shared_mu_f = np.full(p, np.nan)
            shared_sg_f = np.full(p, np.nan)
            shared_mu_f[rows_touched] = sh_mu[last_j]
            shared_sg_f[rows_touched] = sh_sg[last_j]
            # per (row, mode col)
            mkey = prow * (constants.N_MODES + 1) + mode_col_idx
            mk_u, mk_j = last_per(mkey, order)
            col_touched = np.zeros((p, constants.N_MODES + 1), bool)
            mode_val_mu = np.full((p, constants.N_MODES + 1), np.nan)
            mode_val_sg = np.full((p, constants.N_MODES + 1), np.nan)
            rws = mk_u // (constants.N_MODES + 1)
            cls = mk_u % (constants.N_MODES + 1)
            col_touched[rws, cls] = True
            mode_val_mu[rws, cls] = q_mu[mk_j]
            mode_val_sg[rws, cls] = q_sg[mk_j]

            # bitmask per row: bit 0 = shared, bit c = mode col c
            bitmask = np.zeros(p, np.int64)
            bitmask[rows_touched] |= 1
            for c in range(1, constants.N_MODES + 1):
                bitmask[col_touched[:, c]] |= 1 << c
            for bm in np.unique(bitmask):
                if bm == 0:
                    continue
                rws_g = np.flatnonzero(bitmask == bm)
                cols: list[str] = []
                vals: list[np.ndarray] = []
                if bm & 1:
                    for cn, arr in (("trueskill_mu", shared_mu_f),
                                    ("trueskill_sigma", shared_sg_f)):
                        if cn in pl_schema:
                            cols.append(cn)
                            vals.append(arr[rws_g])
                for c in range(1, constants.N_MODES + 1):
                    if bm & (1 << c):
                        base = constants.RATING_COLUMNS[c]
                        for cn, arr in ((f"{base}_mu", mode_val_mu[:, c]),
                                        (f"{base}_sigma", mode_val_sg[:, c])):
                            if cn in pl_schema:
                                cols.append(cn)
                                vals.append(arr[rws_g])
                if not cols:
                    continue
                ids_g = self._player_ids_by_row[rws_g]
                rows = [
                    tuple(float(v[j]) for v in vals) + (ids_g[j],)
                    for j in range(rws_g.size)
                ]
                plan.append(("player", cols, "api_id", rows))

        # participant_items: rated slotted -> any_afk False + the match's
        # mode pair (grouped per mode column); gated matches -> any_afk
        # True on every part's first item (unsupported: untouched).
        it_schema = set(self._schema_rating["participant_items"])
        has_afk_col = "any_afk" in sc["participant_items"]
        item_api = self._item0_api
        for c in range(1, constants.N_MODES + 1):
            base = constants.RATING_COLUMNS[c]
            selc = sl & (mode[self._p_match] + 1 == c)
            if not selc.any():
                continue
            jj = np.flatnonzero(selc)
            cols = []
            if has_afk_col:
                cols.append("any_afk")
            pair = [cn for cn in (f"{base}_mu", f"{base}_sigma")
                    if cn in it_schema]
            cols += pair
            if not cols:
                continue
            i2 = self._p_match[jj]
            t2 = self._s_team[jj]
            s2 = self._slot[jj]
            qm = np.asarray(outs.mode_mu, np.float64)[i2, t2, s2]
            qs = np.asarray(outs.mode_sigma, np.float64)[i2, t2, s2]
            rows = []
            for x, j in enumerate(jj):
                vals: tuple = ()
                if has_afk_col:
                    vals += (False,)
                if f"{base}_mu" in it_schema:
                    vals += (float(qm[x]),)
                if f"{base}_sigma" in it_schema:
                    vals += (float(qs[x]),)
                rows.append(vals + (item_api[j],))
            plan.append(("participant_items", cols, "api_id", rows))
        if has_afk_col:
            gsel = gated[self._p_match]
            rows = [(True, item_api[j]) for j in np.flatnonzero(gsel)]
            if rows:
                plan.append(("participant_items", ["any_afk"], "api_id", rows))
        return plan


def finalize(store, enc, outs) -> None:
    """Applies a batch's outputs through whichever lane ``enc`` is:
    columnar (write_plan -> commit_columnar) or object graph
    (write_back -> commit). The single seam the worker and the pipelined
    writer share, so the two loops cannot disagree on lane selection."""
    plan_fn = getattr(enc, "write_plan", None)
    commit_columnar = getattr(store, "commit_columnar", None)
    if plan_fn is not None and commit_columnar is not None and outs is not None:
        commit_columnar(plan_fn(outs))
        return
    if outs is not None:
        enc.write_back(outs)
    commit = getattr(store, "commit", None)
    if commit is not None and enc.matches:
        commit(enc.matches)
