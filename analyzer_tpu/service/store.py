"""Match/player store: the reference's MySQL object graph, in memory.

The reference reflects its schema at runtime with SQLAlchemy automap
(``worker.py:38-83``): match -> rosters -> participants -> player /
participant_items, plus ``asset`` rows holding telemetry URLs. This store
keeps the same duck-typed object graph (the shape ``rate_match`` and the
parity tests consume) keyed by api_id, with the reference's query contract:
``load_batch(ids)`` dedupes and returns matches ordered by ``created_at``
ascending — the load-bearing ordering of ``worker.py:172,176``.
"""

from __future__ import annotations

from typing import Iterable


class UncloneableStoreError(RuntimeError):
    """The store can never provide a second connection (e.g. in-memory
    sqlite — a new connection sees a different empty database). Raised by
    ``clone()``; the worker treats it as a PERMANENT refusal and disables
    pipelined mode for its lifetime, unlike transient construction
    failures (DB blips), which retry with backoff."""


class InMemoryStore:
    def __init__(self) -> None:
        self.matches: dict[str, object] = {}
        self.assets: dict[str, list[str]] = {}  # match_api_id -> telemetry URLs
        self.players: dict[str, object] = {}

    def add_match(self, match) -> None:
        self.matches[match.api_id] = match
        for p in match.participants:
            player = p.player[0]
            self.players.setdefault(player.api_id, player)

    def add_asset(self, match_api_id: str, url: str) -> None:
        self.assets.setdefault(match_api_id, []).append(url)

    def load_batch(self, ids: Iterable[str]) -> list:
        """Dedupe + chronological order, the ``worker.py:172,176`` contract.
        Unknown ids are skipped (the reference's query simply returns no row
        for them)."""
        seen = dict.fromkeys(ids)  # preserves order, dedupes
        found = [self.matches[i] for i in seen if i in self.matches]
        return sorted(found, key=lambda m: m.created_at)

    def asset_urls(self, match_api_id: str) -> list[str]:
        """The telesuck query: ``SELECT url FROM asset WHERE match_api_id=?``
        (``worker.py:125,150-153``)."""
        return list(self.assets.get(match_api_id, ()))
