"""Service shell: the reference ``worker.py`` re-imagined around the TPU core.

The reference is a RabbitMQ consumer that loads match graphs from MySQL,
rates them one at a time, and fans results out (``worker.py:85-199``). The
shell here keeps its *semantics* — micro-batching with an idle flush,
whole-batch dead-lettering, per-message ack, the notify/crunch/sew/telesuck
fan-out, chronological processing — but the rating path is the vectorized
scheduler + jit-compiled superstep kernel, and the authoritative player
state is the HBM-resident table (the store is a write-behind mirror, not
the source of truth during a batch).

Pluggable edges: ``Broker`` (in-memory always; pika adapter when installed)
and the match store (in-memory object graphs, or ``SqlStore`` — the
reference's reflected-SQL layer on DB-API, sqlite tested end-to-end, MySQL
via gated drivers). Transactionality is by construction: a batch's
outputs are fully computed by pure functions before any mutation is
applied, so an exception mid-compute leaves store and state untouched
(mirroring the reference's single commit/rollback, ``worker.py:194-199``).
"""

from analyzer_tpu.service.broker import Broker, InMemoryBroker, Message
from analyzer_tpu.service.sql_store import SqlStore
from analyzer_tpu.service.store import InMemoryStore
from analyzer_tpu.service.worker import Worker

__all__ = [
    "Broker", "InMemoryBroker", "Message", "InMemoryStore", "SqlStore", "Worker",
]
