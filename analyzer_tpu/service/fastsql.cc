// Columnar sqlite scanner + string-id hash join for SqlStore.load_stream.
//
// The pure-python bulk path (sql_store._sqlite_bulk) walks the table once
// PER COLUMN with group_concat and re-parses the concatenated text in
// numpy — measured 44.5 s for the 1M-match / 7.3M-participant fixture on
// this host (BASELINE.md round 3), single-core parse-bound. This scanner
// walks each query ONCE via the sqlite3 C API into C++ column buffers
// (no per-row Python, no text round-trip, no second sort pass for
// ORDER BY queries), exposed to Python behind an opaque handle; numpy
// arrays are filled by memcpy afterwards.
//
// sq_lookup is the companion join: load_stream maps participant/roster
// TEXT foreign keys to dense row indices, and numpy's S-dtype
// argsort+searchsorted costs ~4.3 s at the same scale — an FNV-1a
// open-addressing hash table over the raw fixed-width bytes does the
// same join in a few hundred ms.
//
// The sqlite3 C ABI has been stable since 2004; the runtime image ships
// libsqlite3.so.0 (the stdlib sqlite3 module links it) but no dev
// package, so the prototypes are declared here and resolved with dlopen
// at first use — no -lsqlite3 at build time, and glibc >= 2.34 folds
// dlopen into libc so the shared build command (native_build.py) needs
// no extra flags. The scanner opens the database READ-ONLY by path: it
// sees committed data only, like the python bulk path's second
// connection.

#include <dlfcn.h>
#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>

#include <string>
#include <vector>

typedef struct sqlite3 sqlite3;
typedef struct sqlite3_stmt sqlite3_stmt;
typedef int64_t i64;
typedef uint64_t u64;

namespace {

struct Api {
  int (*open_v2)(const char *, sqlite3 **, int, const char *);
  int (*prepare_v2)(sqlite3 *, const char *, int, sqlite3_stmt **,
                    const char **);
  int (*step)(sqlite3_stmt *);
  i64 (*column_int64)(sqlite3_stmt *, int);
  double (*column_double)(sqlite3_stmt *, int);
  const unsigned char *(*column_text)(sqlite3_stmt *, int);
  int (*column_bytes)(sqlite3_stmt *, int);
  int (*column_type)(sqlite3_stmt *, int);
  int (*column_count)(sqlite3_stmt *);
  int (*finalize)(sqlite3_stmt *);
  int (*close_db)(sqlite3 *);  // sqlite3_close
  const char *(*errmsg)(sqlite3 *);
};

const int kOpenReadonly = 0x1;
const int kRow = 100;
const int kDone = 101;
const int kOk = 0;
const int kTypeNull = 5;

// Column kinds, matching _native_sql.py's spec encoding.
const int kStr = 0;
const int kInt = 1;
const int kFloat = 2;

void fail(char *err, int errlen, const char *msg) {
  if (err && errlen > 0) {
    snprintf(err, (size_t)errlen, "%s", msg);
  }
}

Api *api(char *err, int errlen) {
  static Api a;
  static int state = 0;  // 0 = untried, 1 = loaded, -1 = unavailable
  if (state == 0) {
    void *h = dlopen("libsqlite3.so.0", RTLD_NOW);
    if (!h) h = dlopen("libsqlite3.so", RTLD_NOW);
    if (!h) {
      state = -1;
    } else {
#define RESOLVE(field, sym)                   \
  a.field = (decltype(a.field))dlsym(h, sym); \
  if (!a.field) state = -1;
      RESOLVE(open_v2, "sqlite3_open_v2")
      RESOLVE(prepare_v2, "sqlite3_prepare_v2")
      RESOLVE(step, "sqlite3_step")
      RESOLVE(column_int64, "sqlite3_column_int64")
      RESOLVE(column_double, "sqlite3_column_double")
      RESOLVE(column_text, "sqlite3_column_text")
      RESOLVE(column_bytes, "sqlite3_column_bytes")
      RESOLVE(column_type, "sqlite3_column_type")
      RESOLVE(column_count, "sqlite3_column_count")
      RESOLVE(finalize, "sqlite3_finalize")
      RESOLVE(close_db, "sqlite3_close")
      RESOLVE(errmsg, "sqlite3_errmsg")
#undef RESOLVE
      if (state == 0) state = 1;
    }
  }
  if (state != 1) {
    fail(err, errlen, "libsqlite3 unavailable");
    return nullptr;
  }
  return &a;
}

struct ScanCol {
  int kind = kStr;
  std::vector<i64> ints;        // kInt
  std::vector<double> floats;   // kFloat
  std::string arena;            // kStr: concatenated bytes...
  std::vector<i64> offs{0};     // ...with nrows+1 offsets
  i64 maxlen = 0;
};

struct Scan {
  i64 nrows = 0;
  std::vector<ScanCol> cols;
};

}  // namespace

// Runs `sql` against the sqlite database at `path` (read-only), buffering
// every column in memory. Returns an opaque handle (free with
// sq_scan_free), or nullptr with `err` filled. NULL values follow the
// python bulk path's conventions: "" for strings, 0 for ints (sqlite's
// own NULL->0 coercion), NaN for floats.
extern "C" void *sq_scan_open(const char *path, const char *sql,
                              int32_t ncols, const int32_t *spec, char *err,
                              int errlen) {
  Api *q = api(err, errlen);
  if (!q) return nullptr;
  sqlite3 *db = nullptr;
  if (q->open_v2(path, &db, kOpenReadonly, nullptr) != kOk || !db) {
    fail(err, errlen, db ? q->errmsg(db) : "sqlite3_open_v2 failed");
    if (db) q->close_db(db);
    return nullptr;
  }
  sqlite3_stmt *st = nullptr;
  if (q->prepare_v2(db, sql, -1, &st, nullptr) != kOk || !st) {
    fail(err, errlen, q->errmsg(db));
    if (st) q->finalize(st);
    q->close_db(db);
    return nullptr;
  }
  if (q->column_count(st) != ncols) {
    fail(err, errlen, "column count mismatch between SQL and spec");
    q->finalize(st);
    q->close_db(db);
    return nullptr;
  }
  Scan *s = new Scan;
  s->cols.resize(ncols);
  for (int c = 0; c < ncols; ++c) s->cols[c].kind = spec[c];
  int rc;
  while ((rc = q->step(st)) == kRow) {
    for (int c = 0; c < ncols; ++c) {
      ScanCol &col = s->cols[c];
      switch (col.kind) {
        case kInt:
          // sqlite coerces TEXT -> int here, matching the python path's
          // text parse; NULL reads as 0 (the COALESCE(col, 0) contract).
          col.ints.push_back(q->column_int64(st, c));
          break;
        case kFloat:
          col.floats.push_back(q->column_type(st, c) == kTypeNull
                                   ? NAN
                                   : q->column_double(st, c));
          break;
        default: {
          const unsigned char *txt = q->column_text(st, c);
          const i64 len = txt ? q->column_bytes(st, c) : 0;
          if (len > 0) col.arena.append((const char *)txt, (size_t)len);
          col.offs.push_back((i64)col.arena.size());
          if (len > col.maxlen) col.maxlen = len;
          break;
        }
      }
    }
    ++s->nrows;
  }
  if (rc != kDone) {
    fail(err, errlen, q->errmsg(db));
    q->finalize(st);
    q->close_db(db);
    delete s;
    return nullptr;
  }
  q->finalize(st);
  q->close_db(db);
  return s;
}

extern "C" i64 sq_scan_nrows(void *h) { return ((Scan *)h)->nrows; }

// Max byte length of a string column's values (its "S" dtype width).
extern "C" i64 sq_scan_width(void *h, int32_t col) {
  return ((Scan *)h)->cols[col].maxlen;
}

// Copies column `col` into a caller-allocated buffer: int64*/double* for
// int/float columns, or a fixed-width (`width` bytes, zero-padded)
// char buffer for string columns. Returns 0, or -1 on a too-small width.
extern "C" int32_t sq_scan_copy(void *h, int32_t col, void *buf, i64 width) {
  Scan *s = (Scan *)h;
  ScanCol &c = s->cols[col];
  switch (c.kind) {
    case kInt:
      memcpy(buf, c.ints.data(), sizeof(i64) * (size_t)s->nrows);
      return 0;
    case kFloat:
      memcpy(buf, c.floats.data(), sizeof(double) * (size_t)s->nrows);
      return 0;
    default: {
      if (width < c.maxlen) return -1;
      char *dst = (char *)buf;
      for (i64 r = 0; r < s->nrows; ++r) {
        const i64 len = c.offs[r + 1] - c.offs[r];
        if (len > 0) memcpy(dst, c.arena.data() + c.offs[r], (size_t)len);
        if (len < width) memset(dst + len, 0, (size_t)(width - len));
        dst += width;
      }
      return 0;
    }
  }
}

extern "C" void sq_scan_free(void *h) { delete (Scan *)h; }

namespace {

// Effective length of a fixed-width ("S" dtype) slot: numpy S-comparison
// ignores trailing NULs, so the join must too.
inline i64 efflen(const char *p, i64 width) {
  while (width > 0 && p[width - 1] == '\0') --width;
  return width;
}

inline u64 fnv1a(const char *p, i64 len) {
  u64 h = 1469598103934665603ull;
  for (i64 i = 0; i < len; ++i) {
    h ^= (u64)(unsigned char)p[i];
    h *= 1099511628211ull;
  }
  return h;
}

}  // namespace

// Occurrence index of each element within its key group, in arrival
// order: out[i] = #{j < i : keys[j] == keys[i]}. Keys must lie in
// [0, minlen) — enforced per element (an out-of-range key returns -2
// instead of corrupting the heap; the contract lived only in a Python
// docstring before). The numpy fallback needs a stable argsort +
// segmented arange (~1.2 s at 9M rows); this is one pass over a dense
// counter array. Returns 0, -1 when the counter allocation fails, or
// -2 on a key outside [0, minlen).
extern "C" int32_t sq_cumcount(const i64 *keys, i64 n, i64 minlen,
                               i64 *out) {
  std::vector<i64> cnt;
  try {
    cnt.assign((size_t)minlen, 0);
  } catch (...) {
    return -1;
  }
  for (i64 i = 0; i < n; ++i) {
    if ((u64)keys[i] >= (u64)minlen) return -2;
    out[i] = cnt[(size_t)keys[i]]++;
  }
  return 0;
}

// Hash join over fixed-width byte-string ids: for each of `nn` needles
// (width nw) find the index of the equal key among `nk` keys (width kw),
// writing it to out[i], or -1 when absent. Duplicate keys resolve to the
// SMALLEST key index (numpy stable argsort + searchsorted-left parity).
// Trailing NUL padding is ignored on both sides. Returns 0, or -1 when
// the table allocation fails.
extern "C" int32_t sq_lookup(const char *keys, i64 kw, i64 nk,
                             const char *needles, i64 nw, i64 nn,
                             i64 *out) {
  u64 cap = 16;
  while ((i64)cap < nk * 2 + 1) cap <<= 1;
  std::vector<i64> slots;
  try {
    slots.assign(cap, -1);
  } catch (...) {
    return -1;
  }
  const u64 mask = cap - 1;
  for (i64 k = 0; k < nk; ++k) {
    const char *kp = keys + k * kw;
    const i64 kl = efflen(kp, kw);
    u64 pos = fnv1a(kp, kl) & mask;
    for (;;) {
      i64 cur = slots[pos];
      if (cur < 0) {
        slots[pos] = k;
        break;
      }
      const char *cp = keys + cur * kw;
      const i64 cl = efflen(cp, kw);
      if (cl == kl && memcmp(cp, kp, (size_t)kl) == 0) {
        break;  // duplicate key: first (smallest) index wins
      }
      pos = (pos + 1) & mask;
    }
  }
  for (i64 i = 0; i < nn; ++i) {
    const char *np_ = needles + i * nw;
    const i64 nl = efflen(np_, nw);
    u64 pos = fnv1a(np_, nl) & mask;
    i64 found = -1;
    for (;;) {
      i64 cur = slots[pos];
      if (cur < 0) break;
      const char *cp = keys + cur * kw;
      const i64 cl = efflen(cp, kw);
      if (cl == nl && memcmp(cp, np_, (size_t)nl) == 0) {
        found = cur;
        break;
      }
      pos = (pos + 1) & mask;
    }
    out[i] = found;
  }
  return 0;
}
