"""SQL match store: the reference's reflected-MySQL layer on raw DB-API.

The reference's L2 is SQLAlchemy automap — the schema is *reflected at
runtime*, never declared in code (``worker.py:43-46``), then the match →
roster → participant → player / participant_items graph is eager-loaded
with ``selectinload`` and written back with one transaction per batch
(``worker.py:169-199``). This adapter keeps every one of those contracts on
plain DB-API 2.0 instead of SQLAlchemy (not installed in this image; an ORM
wrapper would be dead code the tests can never run — the fate the round-1
review flagged for the pika adapter):

  * runtime reflection — table/column sets are discovered from the live
    database (``PRAGMA table_info`` / ``SHOW COLUMNS``), so the loaded
    column set and the write-back column set adapt to the deployed schema
    exactly as automap does; rating columns the schema lacks are silently
    dropped at commit, which is literally automap's behavior (setattr of a
    non-column name is a plain Python attribute the ORM never flushes).
  * selectin eager loading — one query per relationship level keyed by the
    parent ids (``worker.py:176-191``'s ``selectinload`` chain), matches
    ordered by ``created_at`` ascending, ids deduped (``worker.py:172,176``).
  * single-transaction write-back — ``commit()`` flushes every rating
    column of the loaded graph with ``executemany`` and commits once;
    any error rolls back and re-raises (``worker.py:194-199``).
  * ``asset_urls`` — the telesuck query (``SELECT url FROM asset WHERE
    match_api_id = ?``, ``worker.py:150-153``), autocommit read like the
    reference's separate throwaway session (``worker.py:124-126``).

Drivers: ``sqlite://`` URIs use the stdlib ``sqlite3`` (what the tests
exercise end-to-end); ``mysql://`` URIs try the reference's cymysql pin
first (``requirements.txt:1``), then pymysql/MySQLdb — gated imports, same
policy as the pika broker adapter.

Loaded objects are ``types.SimpleNamespace`` graphs shaped exactly like the
parity-test fakes (``tests/fakes.py``; the reference's ``worker_test.py:6-63``
strategy), so the whole encode → rate → write_back path is indifferent to
whether a match came from SQL or memory.
"""

from __future__ import annotations

import dataclasses
from types import SimpleNamespace
from typing import Iterable
from urllib.parse import urlparse, unquote

from analyzer_tpu.core.constants import RATING_COLUMNS
from analyzer_tpu.logging_utils import get_logger

logger = get_logger(__name__)

# The de-facto feature schema of the rating path: the reference's load_only
# column lists (worker.py:176-191). 5v5 columns are absent there and filled
# by lazy loading at runtime in SQLAlchemy; here reflection adds whichever
# rating pairs the live schema actually has (an eager superset, documented
# divergence — there is no lazy loading without an ORM session).
MATCH_COLS = ("api_id", "game_mode", "created_at")
ROSTER_COLS = ("api_id", "match_api_id", "winner")
PARTICIPANT_COLS = (
    "api_id", "match_api_id", "roster_api_id",
    "player_api_id", "skill_tier", "went_afk",
)
PLAYER_BASE_COLS = ("api_id", "rank_points_ranked", "rank_points_blitz")

REQUIRED_TABLES = (
    "match", "asset", "roster", "participant", "participant_items", "player",
)


def _connect(uri: str):
    """Opens a DB-API connection + paramstyle marker for the URI."""
    parsed = urlparse(uri)
    scheme = parsed.scheme.split("+")[0]
    if scheme == "sqlite":
        import sqlite3

        # sqlite:///rel.db | sqlite:////abs.db | sqlite:// (in-memory).
        # A netloc (sqlite://host/x) is not a filesystem path — folding it
        # into one would silently open './host/x'; reject the unsupported
        # host form instead.
        if parsed.netloc:
            raise ValueError(
                f"sqlite URIs take no host: {uri!r} (use sqlite:///rel.db "
                "or sqlite:////abs.db)"
            )
        path = parsed.path or ""
        if path.startswith("/") and not path.startswith("//"):
            path = path[1:]
        elif path.startswith("//"):
            path = path[1:]
        conn = sqlite3.connect(path or ":memory:")
        # The pipelined worker reads on the consumer thread while its
        # writer thread commits on a clone()d connection; without a busy
        # timeout a reader colliding with a commit raises SQLITE_BUSY
        # instead of briefly waiting.
        conn.execute("PRAGMA busy_timeout = 30000")
        return conn, "qmark", "sqlite", (path or None)
    if scheme == "mysql":
        last: Exception | None = None
        for drv in ("cymysql", "pymysql", "MySQLdb"):
            try:
                mod = __import__(drv)
            except ImportError as err:  # gated like the pika adapter
                last = err
                continue
            conn = mod.connect(
                host=parsed.hostname or "localhost",
                port=parsed.port or 3306,
                user=unquote(parsed.username or ""),
                passwd=unquote(parsed.password or ""),
                db=parsed.path.lstrip("/"),
            )
            return conn, "format", "mysql", None
        raise ImportError(
            f"no MySQL driver available for {uri!r} (tried cymysql, pymysql, "
            f"MySQLdb — the reference pins cymysql, requirements.txt:1): {last}"
        )
    raise ValueError(f"unsupported DATABASE_URI scheme: {parsed.scheme!r}")


@dataclasses.dataclass
class ColumnarHistory:
    """:meth:`SqlStore.load_stream`'s result: the full history as tensors
    plus the id maps needed to write results back / trace provenance."""

    stream: object  # sched.MatchStream, chronological
    state: object  # core.PlayerState with DB priors + baked seeds
    match_ids: list  # stream position -> match api_id
    player_ids: list  # player row -> player api_id


class SqlStore:
    """Match store over a SQL database, satisfying the worker's store
    protocol (``load_batch``, ``asset_urls``) plus the transactional
    ``commit``/``rollback`` the reference performs per batch.

    ``chunk_size`` bounds per-query row batches (the IN-list split in
    ``_select_in``) — the DB-API analog of the reference's
    ``yield_per(CHUNKSIZE)`` row streaming (``worker.py:19,191``)."""

    def __init__(self, uri: str, chunk_size: int = 100) -> None:
        self.uri = uri
        self.chunk_size = max(int(chunk_size), 1)
        (self.conn, self._paramstyle, self._dialect,
         self._sqlite_path) = _connect(uri)
        self._native_sql: bool | None = None  # False once proven unbuildable
        self.columns = self._reflect()
        missing = [t for t in REQUIRED_TABLES if t not in self.columns]
        if missing:
            raise RuntimeError(
                f"schema reflection: required tables missing from {uri!r}: "
                f"{missing} (the reference reflects match/asset/roster/"
                "participant/participant_stats/participant_items/player, "
                "worker.py:50-83)"
            )
        # participant_stats is reflected but never loaded nor written —
        # the reference wires it (worker.py:75-78) and never touches it.
        self._rating_cols = {
            table: [
                c
                for col in RATING_COLUMNS
                for c in (f"{col}_mu", f"{col}_sigma")
                if c in self.columns[table]
            ]
            for table in ("player", "participant_items")
        }

    def enable_wal(self) -> bool:
        """SERVICE-LANE journal mode: WAL lets the consumer thread's
        selectin loads proceed WHILE the writer thread's clone commits
        (delete-journal commits take an exclusive lock that stalls
        readers — measured as the pipelined sqlite lane's contention
        floor) and roughly halves the per-batch commit (append to the
        log, no full-db journal). synchronous=NORMAL under WAL keeps
        integrity across app crashes and loses at most the last commits
        on an OS crash — the same at-least-once window the broker's
        redelivery already covers (an unacked batch re-rates
        idempotently).

        Called by ``Worker.__init__``, NOT at connect: WAL is the wrong
        trade for the BULK lane — the full-history scans and bulk
        write-back measured 1.7x slower under WAL (22.6 s vs 13.3 s
        load_stream at 1M matches, round 5; every read checks the
        wal/shm, and scattered bulk updates pay the write-twice
        amplification), and that lane is single-threaded with nothing to
        overlap. The pragma REPORTS failure instead of raising (returns
        the old mode); synchronous is relaxed only when WAL actually
        engaged — in rollback-journal mode NORMAL opens a power-loss
        corruption window, not just a lost-commit one. Returns whether
        WAL is active. Note the mode is a property of the database FILE:
        it persists for later connections until changed back."""
        if self._dialect != "sqlite" or self._sqlite_path is None:
            return False
        try:
            got = self.conn.execute("PRAGMA journal_mode = WAL").fetchone()
            if got and str(got[0]).lower() == "wal":
                self.conn.execute("PRAGMA synchronous = NORMAL")
                return True
        except Exception:  # pragma: no cover — e.g. network fs
            pass
        return False

    def clone(self) -> "SqlStore":
        """A second store handle on its OWN connection — the pipelined
        worker's writer thread commits through a clone while the consumer
        thread keeps loading (sqlite connections are bound to the thread
        that may use them; MySQL connections are not thread-safe either).
        In-memory sqlite cannot be cloned (a new connection sees a
        different empty database) nor shared across threads
        (``check_same_thread``) — raises UncloneableStoreError so the
        worker permanently falls back to the sequential loop instead of
        failing batches (transient failures retry instead)."""
        from analyzer_tpu.service.store import UncloneableStoreError

        if self._dialect == "sqlite" and self._sqlite_path is None:
            raise UncloneableStoreError(
                "in-memory sqlite store cannot be used by the pipelined "
                "worker (no second connection can see it); use a "
                "file-backed database or PIPELINE=false"
            )
        return SqlStore(self.uri, chunk_size=self.chunk_size)

    # -- reflection -------------------------------------------------------
    def _reflect(self) -> dict[str, list[str]]:
        cur = self.conn.cursor()
        out: dict[str, list[str]] = {}
        if self._dialect == "sqlite":
            cur.execute(
                "SELECT name FROM sqlite_master WHERE type='table'"
            )
            tables = [r[0] for r in cur.fetchall()]
            for t in tables:
                cur.execute(f'PRAGMA table_info("{t}")')
                out[t] = [r[1] for r in cur.fetchall()]
        else:
            cur.execute("SHOW TABLES")
            tables = [r[0] for r in cur.fetchall()]
            for t in tables:
                cur.execute(f"SHOW COLUMNS FROM `{t}`")
                out[t] = [r[0] for r in cur.fetchall()]
        cur.close()
        return out

    # -- query helpers ----------------------------------------------------
    def _ph(self, n: int) -> str:
        mark = "?" if self._paramstyle == "qmark" else "%s"
        return ",".join([mark] * n)

    def _q(self, name: str) -> str:
        return f'"{name}"' if self._dialect == "sqlite" else f"`{name}`"

    def _select_in(self, table: str, cols: Iterable[str], key: str,
                   values: list, order_by: str | None = None) -> list[tuple]:
        if not values:
            return []
        cols = list(cols)
        cur = self.conn.cursor()
        # Chunk the IN list (the reference bounds per-query row streaming
        # with yield_per(CHUNKSIZE), worker.py:19,191; huge IN lists are
        # the DB-API analog of that concern).
        step = self.chunk_size
        rows: list[tuple] = []
        for i in range(0, len(values), step):
            chunk = values[i : i + step]
            sql = (
                f"SELECT {', '.join(self._q(c) for c in cols)} "
                f"FROM {self._q(table)} "
                f"WHERE {self._q(key)} IN ({self._ph(len(chunk))})"
            )
            if order_by:
                sql += f" ORDER BY {self._q(order_by)} ASC"
            cur.execute(sql, chunk)
            rows.extend(cur.fetchall())
        cur.close()
        if order_by and len(values) > step:
            idx = cols.index(order_by)
            # NULL-safe merge of the per-chunk ORDER BYs: None cannot be
            # compared to str/datetime in python; sqlite sorts NULLs
            # first, so mirror that.
            # Tuple keys never compare the second element across the
            # None/non-None boundary (the bool decides), and equal Nones
            # need no ordering call.
            rows.sort(key=lambda r: (r[idx] is not None, r[idx]))
        return rows

    # -- store protocol ---------------------------------------------------
    def load_batch(self, ids: Iterable[str]) -> list:
        """Dedupe + load the eager object graph, matches ordered by
        ``created_at`` ascending (``worker.py:172,176-191``). Built from
        the SAME raw row bundle as the columnar lane
        (:meth:`load_batch_raw`) — one definition of the five selectin
        queries, so the two lanes cannot drift on the load-bearing
        arrival orders (roster arrival defines team 0/1, participant
        arrival defines slots)."""
        raw = self.load_batch_raw(ids)
        matches: list[SimpleNamespace] = []
        for api_id, game_mode, created_at in raw["match_rows"]:
            matches.append(SimpleNamespace(
                api_id=api_id, game_mode=game_mode, created_at=created_at,
                trueskill_quality=None, rosters=[], participants=[],
            ))

        by_match: dict[str, SimpleNamespace] = {m.api_id: m for m in matches}
        rosters: dict[str, SimpleNamespace] = {}
        for api_id, match_api_id, winner in raw["roster_rows"]:
            r = SimpleNamespace(
                api_id=api_id, match_api_id=match_api_id, winner=winner,
                participants=[],
            )
            rosters[api_id] = r
            by_match[match_api_id].rosters.append(r)

        part_rows = raw["part_rows"]
        # Absent schema columns read as None. Computed ONCE per batch:
        # the per-object hasattr probe over every rating pair cost ~90k
        # dynamic attribute checks per 500-match batch (~30% of
        # load_batch, profiled round 5) for an answer that is a property
        # of the reflected schema, not of any row.
        player_cols = raw["player_cols"]
        base = {"skill_tier": None}
        for col in RATING_COLUMNS:
            base[f"{col}_mu"] = None
            base[f"{col}_sigma"] = None
        players: dict[str, SimpleNamespace] = {}
        for row in raw["player_rows"]:
            p = SimpleNamespace(**base)
            p.__dict__.update(zip(player_cols, row))
            players[p.api_id] = p

        items_cols = raw["items_cols"]
        items_base = {}
        for col in RATING_COLUMNS[1:]:
            items_base[f"{col}_mu"] = None
            items_base[f"{col}_sigma"] = None
        items_by_part: dict[str, list[SimpleNamespace]] = {}
        for row in raw["items_rows"]:
            it = SimpleNamespace(**items_base)
            it.__dict__.update(zip(items_cols, row))
            items_by_part.setdefault(it.participant_api_id, []).append(it)

        for api_id, match_api_id, roster_api_id, player_api_id, skill_tier, went_afk in part_rows:
            part = SimpleNamespace(
                api_id=api_id,
                match_api_id=match_api_id,
                roster_api_id=roster_api_id,
                player_api_id=player_api_id,
                skill_tier=skill_tier,
                went_afk=went_afk,
                trueskill_mu=None,
                trueskill_sigma=None,
                trueskill_delta=None,
                player=[players[player_api_id]],
                participant_items=items_by_part.get(api_id, []),
            )
            by_match[match_api_id].participants.append(part)
            if roster_api_id in rosters:
                rosters[roster_api_id].participants.append(part)
        return matches

    # -- columnar batch lane ----------------------------------------------
    def load_batch_raw(self, ids: Iterable[str]):
        """The ONE implementation of the batch's five selectin queries
        (dedupe, created_at order, arrival orders), returned as raw row
        bundles. :class:`analyzer_tpu.service.columnar.ColumnarBatch`
        consumes them directly (no object graphs — on this package's
        1-core reference host the ~11k-SimpleNamespace build was the
        single largest python cost of the service loop, profiled round
        5); :meth:`load_batch` builds the duck-typed object graph from
        the same bundle. player.skill_tier is not in the reference's
        load_only list (worker.py:184-190) but get_trueskill_seed reads
        it lazily (rater.py:57-60); reflection loads it eagerly when it
        exists."""
        seen = list(dict.fromkeys(ids))
        match_rows = self._select_in(
            "match", MATCH_COLS, "api_id", seen, order_by="created_at"
        )
        mids = [r[0] for r in match_rows]
        roster_rows = self._select_in(
            "roster", ROSTER_COLS, "match_api_id", mids
        )
        part_rows = self._select_in(
            "participant", PARTICIPANT_COLS, "match_api_id", mids
        )
        player_ids = list(dict.fromkeys(r[3] for r in part_rows))
        player_cols = list(PLAYER_BASE_COLS) + self._rating_cols["player"]
        if "skill_tier" in self.columns["player"]:
            player_cols.insert(len(PLAYER_BASE_COLS), "skill_tier")
        player_rows = self._select_in("player", player_cols, "api_id", player_ids)
        items_cols = ["api_id", "participant_api_id", "any_afk"]
        items_cols += self._rating_cols["participant_items"]
        part_ids = [r[0] for r in part_rows]
        items_rows = self._select_in(
            "participant_items", items_cols, "participant_api_id", part_ids
        )
        return {
            "match_rows": match_rows,
            "roster_rows": roster_rows,
            "part_rows": part_rows,
            "player_cols": player_cols,
            "player_rows": player_rows,
            "items_cols": items_cols,
            "items_rows": items_rows,
            "schema_rating_cols": dict(self._rating_cols),
            # Full column sets of the write-target tables, so write_plan
            # can apply the object lane's filter-before-building rule
            # (columns the deployed schema lacks are dropped, exactly as
            # automap never flushes a non-column attribute).
            "schema_columns": {
                t: set(self.columns[t])
                for t in ("match", "participant", "player", "participant_items")
            },
        }

    def load_batch_native(self, ids: Iterable[str]):
        """[sqlite fastest path] The five batch queries through the C
        columnar scanner (``fastsql.cc``): columns arrive as typed numpy
        arrays with NO per-row python tuples — ``fetchall``'s tuple
        building was the largest single cost of the columnar lane's
        load (~58 ms of a 500-match batch, profiled round 5 on-rig).
        Returns an array-form bundle for :class:`ColumnarBatch`, or None
        when the native layer is unavailable (file-less DB, no g++, scan
        failure, an id the literal quoting cannot carry) — callers fall
        back to :meth:`load_batch_raw`.

        Ties in ``created_at`` may order differently than the python
        lane's chunked merge (both are within the reference's
        unspecified tie behavior, ``worker.py:176``); team/slot arrival
        orders can likewise differ for >CHUNKSIZE batches — all
        rating-output-neutral (the kernel is team-symmetric given the
        winner flag; outputs key by player)."""
        if self._sqlite_path is None or self._native_sql is False:
            return None
        seen = list(dict.fromkeys(ids))
        if not seen:
            return None  # empty loads take the (trivial) python path
        for v in seen:
            if "\x00" in str(v):
                return None  # a literal cannot carry NUL; bind path can
        inlist = ",".join("'" + str(v).replace("'", "''") + "'" for v in seen)
        q = self._q
        m = self._native_scan(
            f"SELECT {q('api_id')}, {q('game_mode')} FROM {q('match')} "
            f"WHERE {q('api_id')} IN ({inlist}) "
            f"ORDER BY {q('created_at')} ASC",
            [("api_id", "str"), ("game_mode", "str")],
        )
        if m is None:
            return None
        mid_list = ",".join(
            "'" + s.decode().replace("'", "''") + "'" for s in m["api_id"]
        )
        if not mid_list:
            mid_list = "''"
        ro = self._native_scan(
            f"SELECT {q('api_id')}, {q('match_api_id')}, {q('winner')} "
            f"FROM {q('roster')} WHERE {q('match_api_id')} IN ({mid_list})",
            [("api_id", "str"), ("match_api_id", "str"), ("winner", "int")],
        )
        pa = self._native_scan(
            f"SELECT {q('api_id')}, {q('match_api_id')}, "
            f"{q('roster_api_id')}, {q('player_api_id')}, {q('went_afk')} "
            f"FROM {q('participant')} "
            f"WHERE {q('match_api_id')} IN ({mid_list})",
            [("api_id", "str"), ("match_api_id", "str"),
             ("roster_api_id", "str"), ("player_api_id", "str"),
             ("went_afk", "int")],
        )
        if ro is None or pa is None:
            return None
        pid_set = dict.fromkeys(pa["player_api_id"].tolist())
        pid_list = ",".join(
            "'" + s.decode().replace("'", "''") + "'" for s in pid_set
        ) or "''"
        player_cols = list(PLAYER_BASE_COLS) + self._rating_cols["player"]
        if "skill_tier" in self.columns["player"]:
            player_cols.insert(len(PLAYER_BASE_COLS), "skill_tier")
        # Every non-id column as float: NULL -> NaN keeps a missing
        # skill_tier distinguishable from tier 0 for the out-of-table
        # gate (the scanner's int convention folds NULL into 0).
        spec = [("api_id", "str")] + [(c, "float") for c in player_cols[1:]]
        pl = self._native_scan(
            f"SELECT {', '.join(q(c) for c, _ in spec)} FROM {q('player')} "
            f"WHERE {q('api_id')} IN ({pid_list})",
            spec,
        )
        paid_list = ",".join(
            "'" + s.decode().replace("'", "''") + "'" for s in pa["api_id"]
        ) or "''"
        it = self._native_scan(
            f"SELECT {q('api_id')}, {q('participant_api_id')} "
            f"FROM {q('participant_items')} "
            f"WHERE {q('participant_api_id')} IN ({paid_list})",
            [("api_id", "str"), ("participant_api_id", "str")],
        )
        if pl is None or it is None:
            return None
        return {
            "match": m,
            "roster": ro,
            "participant": pa,
            "player": pl,
            "player_cols": player_cols,
            "items": it,
            "schema_rating_cols": dict(self._rating_cols),
            "schema_columns": {
                t: set(self.columns[t])
                for t in ("match", "participant", "player",
                          "participant_items")
            },
        }

    def commit_columnar(self, plan) -> None:
        """Array-lane counterpart of :meth:`commit`: flushes a
        :meth:`ColumnarBatch.write_plan` in one transaction. The plan
        writes ONLY touched columns/rows (exactly what the reference's
        ORM flush would — automap never writes unmodified attributes),
        which both shrinks the bind work and removes the object lane's
        stale-snapshot rewrite hazard under pipelining (columnar.py)."""
        try:
            cur = self.conn.cursor()
            mark = "?" if self._paramstyle == "qmark" else "%s"
            for table, cols, key, rows in plan:
                # No schema re-filtering here: the plan was built FROM
                # the reflected schema (load_batch_raw ships
                # schema_rating_cols), and rows are positional — dropping
                # a column without its values would shift every bind.
                if not rows or not cols:
                    continue
                sql = (
                    f"UPDATE {self._q(table)} SET "
                    + ", ".join(f"{self._q(c)} = {mark}" for c in cols)
                    + f" WHERE {self._q(key)} = {mark}"
                )
                cur.executemany(sql, rows)
            cur.close()
            self.conn.commit()
        except Exception:
            self.conn.rollback()
            raise

    # -- columnar full-history ingest -------------------------------------
    def _sqlite_bulk(
        self, table: str, str_cols: tuple, int_cols: tuple,
        float_cols: tuple = (), chunk_rows: int = 4_000_000,
    ) -> dict:
        """[sqlite fast path] Every row of ``table``, rowid-ordered, as
        numpy column arrays — WITHOUT per-row Python tuples.

        Each (rowid range, column) pair issues ONE ``group_concat``
        aggregate: the whole scan executes inside a single
        ``sqlite3_step`` call with no per-row Python (the classic
        fetchall path builds a tuple per row — measured 94 s for 7.3M
        participant rows on the 1M-match fixture vs ~10 s this way; the
        indexed-JOIN alternative was 128 s). Alignment is safe by
        construction: a rowid-range query walks the table b-tree in rowid
        order, and every nullable column is COALESCEd so no accumulator
        skips a row — the per-chunk length check still guards it.
        Chunking keeps each concat far below SQLITE_MAX_LENGTH and bounds
        peak memory.
        """
        import sqlite3

        import numpy as np

        q = self._q
        cur = self.conn.cursor()
        cur.execute(f"SELECT MIN(rowid), MAX(rowid) FROM {q(table)}")
        lo, hi = cur.fetchone()
        cur.close()
        empty = {c: np.empty(0, "S1") for c in str_cols}
        empty.update({c: np.empty(0, np.int64) for c in int_cols})
        empty.update({c: np.empty(0, np.float64) for c in float_cols})
        if lo is None:
            return empty
        # Row order: a `WHERE rowid BETWEEN` range query walks the table
        # b-tree itself, which IS rowid order — no per-row rowid column
        # needed (concatenating one would double the aggregate work). The
        # per-column buffers of one chunk therefore align by construction;
        # the length check below still guards it (COALESCE keeps every
        # accumulator from skipping NULL rows).
        ranges = [
            (a, min(a + chunk_rows - 1, hi))
            for a in range(lo, hi + 1, chunk_rows)
        ]
        cols = [*str_cols, *int_cols, *float_cols]

        # One extra connection for the scans (bytes text factory without
        # disturbing the main connection); :memory: databases fall back
        # to the main connection — their data is invisible to new ones.
        # Scans run SEQUENTIALLY on purpose: concurrent readers of one
        # sqlite file anti-scale (measured on the 1M-match fixture: the
        # participant scans took 9.6 s serial, 24 s with two threads,
        # 30 s with three — contention swamps the extra core).
        if self._sqlite_path is not None:
            conn = sqlite3.connect(self._sqlite_path)
        else:
            conn = self.conn
        prev_factory = conn.text_factory
        conn.text_factory = bytes
        by_col: dict[str, list[np.ndarray]] = {c: [] for c in cols}
        try:
            c = conn.cursor()
            for ri, _ in enumerate(ranges):
                sizes = set()
                for col in cols:
                    # 'nan' for float columns: numpy's float parser turns
                    # it back into NaN, so SQL NULL round-trips without a
                    # sparse query.
                    fill = (
                        "''" if col in str_cols
                        else "0" if col in int_cols else "'nan'"
                    )
                    c.execute(
                        f"SELECT group_concat(COALESCE({q(col)}, {fill}), "
                        f"x'0a') FROM {q(table)} WHERE rowid BETWEEN ? AND ?",
                        ranges[ri],
                    )
                    buf = c.fetchone()[0]
                    if buf is None:
                        sizes.add(0)
                        continue
                    # Parse IMMEDIATELY so the raw text buffer frees per
                    # column — peak memory is one column's text plus the
                    # arrays, not every buffer at once.
                    raw = buf.split(b"\n")
                    del buf
                    sizes.add(len(raw))
                    dt = (
                        None if col in str_cols
                        else np.int64 if col in int_cols else np.float64
                    )
                    by_col[col].append(
                        np.array(raw) if dt is None else np.array(raw, dt)
                    )
                if len(sizes) > 1:  # COALESCE guarantees alignment; fail loudly
                    raise RuntimeError(
                        f"bulk scan of {table}: misaligned column lengths "
                        f"{sizes}"
                    )
            c.close()
        finally:
            if conn is not self.conn:
                conn.close()
            else:
                conn.text_factory = prev_factory
        if not any(by_col[c] for c in cols):
            return empty
        return {c: np.concatenate(by_col[c]) for c in cols}

    def _generic_bulk(
        self, table: str, str_cols: tuple, int_cols: tuple,
        float_cols: tuple = (),
    ) -> dict:
        """Portable bulk fetch (MySQL): plain SELECT ordered by api_id —
        no rowid exists, so arrival order is the primary key (documented
        ordering divergence of the bulk path on MySQL)."""
        import numpy as np

        q = self._q
        cur = self.conn.cursor()
        cols = [*str_cols, *int_cols, *float_cols]
        cur.execute(
            f"SELECT {', '.join(q(c) for c in cols)} FROM {q(table)} "
            f"ORDER BY {q('api_id')} ASC"
        )
        rows = cur.fetchall()
        cur.close()
        out = {}
        for i, c in enumerate(str_cols):
            out[c] = np.array([r[i] or "" for r in rows]) if rows else np.empty(0, "U1")
        base = len(str_cols)
        for i, c in enumerate(int_cols):
            out[c] = (
                np.fromiter((r[base + i] or 0 for r in rows), np.int64, len(rows))
                if rows else np.empty(0, np.int64)
            )
        base += len(int_cols)
        for i, c in enumerate(float_cols):
            out[c] = (
                np.fromiter(
                    (np.nan if r[base + i] is None else r[base + i] for r in rows),
                    np.float64, len(rows),
                )
                if rows else np.empty(0, np.float64)
            )
        return out

    def _native_scan(self, sql: str, cols: list) -> "dict | None":
        """[sqlite fastest path] Arbitrary-query columnar scan through the
        C sqlite reader (``fastsql.cc``): one b-tree walk per pass with no
        per-row Python and no text round-trip for numeric columns —
        measured ~4x faster than the group_concat scan on the 1M-match
        fixture. Opens the database read-only BY PATH, so it sees
        committed data only (the same visibility as ``_sqlite_bulk``'s
        second connection). Returns None when the native layer is
        unavailable or the scan fails (callers fall back to the python
        scans); in-memory databases never take this path.
        """
        if self._sqlite_path is None or self._native_sql is False:
            return None
        try:
            from analyzer_tpu.service import _native_sql
        except ImportError as e:
            self._native_sql = False  # no g++ / unloadable .so: stop trying
            logger.warning("native sqlite scanner unavailable (%s); "
                           "using python bulk scans", e)
            return None
        try:
            return _native_sql.scan_query(self._sqlite_path, sql, cols)
        except RuntimeError as e:  # db changed mid-scan, odd page, ...
            logger.warning("native sqlite scan failed (%s); "
                           "falling back to python scan for: %s", e, sql)
            return None

    def _bulk(
        self, table: str, str_cols: tuple, int_cols: tuple = (),
        float_cols: tuple = (),
    ) -> dict:
        if self._dialect == "sqlite":
            q = self._q
            cols = (
                [(c, "str") for c in str_cols]
                + [(c, "int") for c in int_cols]
                + [(c, "float") for c in float_cols]
            )
            native = self._native_scan(
                f"SELECT {', '.join(q(c) for c, _ in cols)} FROM {q(table)} "
                f"ORDER BY rowid ASC",
                cols,
            )
            if native is not None:
                return native
            return self._sqlite_bulk(table, str_cols, int_cols, float_cols)
        return self._generic_bulk(table, str_cols, int_cols, float_cols)

    def load_stream(self, cfg=None) -> "ColumnarHistory":
        """Columnar DB -> tensor ingest: the full match history SELECTed
        straight into numpy arrays, no object graphs.

        ``load_batch`` + ``EncodedBatch`` are right for service batches of
        500; a full-history re-rate FROM the database (the reference's
        actual data source, ``worker.py:176-191``) would pay millions of
        SimpleNamespace allocations just to re-flatten them. Here the
        heavy tables stream out through :meth:`_bulk` (parallel
        GIL-releasing scans on sqlite), and all id -> dense-index mapping
        is vectorized numpy (``argsort`` + ``searchsorted`` over the id
        arrays; per-roster team numbers and per-team slots are grouped
        cumcounts). Matches are ordered by ``created_at`` ascending — the
        load-bearing order (``worker.py:176``) — with the database doing
        that one type-aware sort. Player priors/seed features fill the
        packed state table via sparse ``IS NOT NULL`` selects (NULL stays
        NaN).

        Documented divergences from the object path (all logged):
          * malformed matches — roster count != 2, team slot overflow,
            zero/two winner flags — are marked NON-RATABLE instead of
            raising; one corrupt record must not kill a 10M-match ingest
            (``EncodedBatch`` stays strict for service batches).
          * out-of-table skill tiers are clamped (tensor-path semantics);
            the object API's KeyError contract needs per-match gating
            this bulk path does not reconstruct.
          * dangling foreign keys (roster without its match, participant
            without its roster/player) are dropped, like the inner joins
            the object path's dict lookups amount to.

        Returns a :class:`ColumnarHistory`; pass its ``state``/``stream``
        to ``sched.rate_stream`` / ``rate_history`` and optionally write
        the final table back with :meth:`write_players`.
        """
        import numpy as np

        from analyzer_tpu.config import RatingConfig
        from analyzer_tpu.core import constants
        from analyzer_tpu.core.seeding import trueskill_seed_host
        from analyzer_tpu.core.state import (
            COL_SEED_MU, COL_SEED_SIGMA, MAX_TEAM_SIZE, MU_LO, SIGMA_LO,
            TABLE_WIDTH, PlayerState,
        )
        from analyzer_tpu.sched.superstep import MatchStream

        import jax.numpy as jnp

        cfg = cfg or RatingConfig()
        q = self._q
        sqlite = self._dialect == "sqlite"
        if sqlite and self._sqlite_path is not None:
            try:
                got = self.conn.execute("PRAGMA journal_mode").fetchone()
                if got and str(got[0]).lower() == "wal":
                    # A service worker owned this file at some point (the
                    # mode persists). The bulk scans measured ~1.7x
                    # slower under WAL — tell the operator rather than
                    # silently flipping their database's mode.
                    logger.warning(
                        "database is in WAL journal mode (set by a "
                        "service worker); the bulk ingest runs ~1.7x "
                        "faster under the rollback journal — consider "
                        "'PRAGMA journal_mode=DELETE' for large offline "
                        "re-rates (docs/OPERATIONS.md)"
                    )
            except Exception:  # pragma: no cover — advisory only
                pass
        cur = self.conn.cursor()

        def _decode(x):
            return x.decode() if isinstance(x, bytes) else x

        def _decode_list(arr) -> list:
            """Vectorized id-array -> list[str] (np.char.decode runs the
            utf-8 decode in a C loop; the per-element comprehension cost
            0.7 s at 1.3M ids)."""
            if arr.dtype.kind == "S":
                return np.char.decode(arr, "utf-8").tolist()
            return [_decode(x) for x in arr]

        native_join = None
        if sqlite and self._native_sql is not False:
            try:
                from analyzer_tpu.service import _native_sql

                native_join = _native_sql.lookup
            except ImportError as e:
                # Latch like _native_scan does: a failed build would
                # otherwise re-spawn g++ for every fresh store.
                self._native_sql = False
                logger.warning("native sqlite scanner unavailable (%s); "
                               "using numpy joins", e)

        def _join(ids, needles):
            """needle -> position in ``ids``; ok=False for misses. Native
            hash join when available (S-dtype ids), else the numpy
            argsort+searchsorted path — identical semantics, including
            smallest-index resolution of duplicate ids."""
            if (
                native_join is not None
                and ids.dtype.kind == "S"
                and needles.dtype.kind == "S"
            ):
                got = native_join(ids, needles)
                ok = got >= 0
                return np.where(ok, got, 0), ok
            sorted_ids, order = _index(ids)
            return _lookup(sorted_ids, order, needles)

        def _index(ids):
            """Sorted view of an id array for searchsorted lookups."""
            order = np.argsort(ids, kind="stable")
            return ids[order], order

        def _lookup(sorted_ids, order, needles):
            """needle -> position in the ORIGINAL id array; ok=False for
            misses (dangling foreign keys)."""
            if sorted_ids.size == 0 or needles.size == 0:
                return (np.zeros(needles.shape, np.int64),
                        np.zeros(needles.shape, bool))
            pos = np.searchsorted(sorted_ids, needles)
            pos = np.minimum(pos, sorted_ids.size - 1)
            got = order[pos]
            return got, sorted_ids[pos] == needles

        def _cumcount(keys, minlength=None):
            """Occurrence index of each element within its key group,
            preserving arrival order (stable). ``minlength`` bounds the
            key values and routes through the native single-pass counter
            when available — unless the bound is degenerate (a malformed
            match with hundreds of rosters inflates the slot stride, and
            with it the dense counter) or the allocation fails; the numpy
            path's cost is independent of the key range."""
            if keys.size == 0:
                return np.zeros(0, np.int64)
            if (
                native_join is not None
                and minlength is not None
                and minlength <= 16 * keys.size
            ):
                try:
                    return _native_sql.cumcount(keys, minlength)
                except RuntimeError as e:
                    logger.warning(
                        "native cumcount failed (%s); using numpy path", e
                    )
            order = np.argsort(keys, kind="stable")
            sk = keys[order]
            first = np.r_[True, sk[1:] != sk[:-1]]
            start = np.maximum.accumulate(
                np.where(first, np.arange(sk.size), 0)
            )
            out = np.empty(sk.size, np.int64)
            out[order] = np.arange(sk.size) - start
            return out

        # -- matches: the one type-aware sort the database owns ----------
        tie = "rowid" if sqlite else q("api_id")
        match_sql = (
            f"SELECT {q('api_id')}, {q('game_mode')} FROM {q('match')} "
            f"ORDER BY {q('created_at')} ASC, {tie} ASC"
        )
        native = (
            self._native_scan(
                match_sql, [("api_id", "str"), ("game_mode", "str")]
            ) if sqlite else None
        )
        if native is not None:
            m_ids = native["api_id"]
            modes = native["game_mode"]
            n = int(m_ids.size)
        else:
            # The bytes factory window is scoped to THIS fetch
            # (try/finally): leaking it past an exception would leave
            # every later load_batch/asset_urls on this store returning
            # bytes ids.
            if sqlite:
                prev_factory = self.conn.text_factory
                self.conn.text_factory = bytes
            try:
                cur.execute(match_sql)
                m_rows = cur.fetchall()
            finally:
                if sqlite:
                    self.conn.text_factory = prev_factory
            n = len(m_rows)
            nil = b"" if sqlite else ""
            m_ids = (
                np.array([r[0] for r in m_rows]) if n else np.empty(0, "S1")
            )
            modes = (
                np.array([r[1] or nil for r in m_rows])
                if n else np.empty(0, "S1")
            )
            del m_rows
        mode_id = np.full(n, constants.UNSUPPORTED_MODE_ID, np.int32)
        for name, mid in constants.MODE_TO_ID.items():
            key = name.encode() if sqlite else name
            mode_id[modes == key] = mid
        del modes

        # -- players: one bulk pass over every feature/prior column ------
        pcols = self.columns["player"]
        p_int = tuple(c for c in ("skill_tier",) if c in pcols)
        p_float = tuple(
            c for c in ("rank_points_ranked", "rank_points_blitz")
            if c in pcols
        ) + tuple(self._rating_cols["player"])
        pl = self._bulk("player", ("api_id",), p_int, p_float)
        p_ids = pl["api_id"]
        p = int(p_ids.size)

        # -- rosters -----------------------------------------------------
        ro = self._bulk(
            "roster", ("api_id", "match_api_id"), ("winner",)
        )
        r_mid, r_ok = _join(m_ids, ro["match_api_id"])
        if not r_ok.all():
            logger.warning(
                "load_stream: dropped %d rosters with missing matches",
                int((~r_ok).sum()),
            )
        r_ids = ro["api_id"][r_ok]
        r_mid = r_mid[r_ok]
        r_win = ro["winner"][r_ok]
        del ro
        team = _cumcount(r_mid, minlength=n)  # arrival order within match
        roster_count = np.bincount(r_mid, minlength=n)
        bad = roster_count != 2  # rater.py:91-93 validity gate

        # Winner flags: exactly one winning roster per match; ties (0 or
        # 2 winners) are non-ratable here (the service path stays strict).
        wflag = np.zeros((n, 2), bool)
        in_team = team < 2
        wflag[r_mid[in_team], team[in_team]] = r_win[in_team] != 0
        tie_m = ~bad & (wflag[:, 0] == wflag[:, 1])
        winner = np.where(wflag[:, 0], 0, 1).astype(np.int32)

        # -- participants ------------------------------------------------
        pa = self._bulk(
            "participant", ("roster_api_id", "player_api_id"), ("went_afk",)
        )
        pr, ok_r = _join(r_ids, pa["roster_api_id"])
        prow, ok_p = _join(p_ids, pa["player_api_id"])
        ok = ok_r & ok_p
        if not ok.all():
            logger.warning(
                "load_stream: dropped %d participants with dangling "
                "roster/player references", int((~ok).sum()),
            )
        midx_p = r_mid[pr[ok]]
        team_p = team[pr[ok]]
        pidx_p = prow[ok]
        afk_p = pa["went_afk"][ok]
        del pa
        # Slot = arrival order within (match, team). The stride must
        # exceed the LARGEST team index present — a malformed match with
        # a third roster would otherwise collide its team-2 key with the
        # next match's team-0 key and corrupt a well-formed neighbor's
        # slot numbering.
        stride = int(team_p.max()) + 1 if team_p.size else 1
        slot = _cumcount(midx_p * stride + team_p, minlength=n * stride)

        player_idx = np.full((n, 2, MAX_TEAM_SIZE), -1, np.int32)
        fits = (team_p < 2) & (slot < MAX_TEAM_SIZE)
        overflow = np.zeros(n, bool)
        if not fits.all():  # team/slot overflow -> non-ratable, not fatal
            overflow[midx_p[~fits]] = True
        player_idx[midx_p[fits], team_p[fits], slot[fits]] = pidx_p[fits]
        afk = np.zeros(n, bool)
        afk[midx_p[afk_p == 1]] = True

        if (overflow | tie_m).any():
            logger.warning(
                "load_stream: %d malformed matches marked non-ratable "
                "(%d team/slot overflow, %d winner-flag ties)",
                int((overflow | tie_m).sum()), int(overflow.sum()),
                int(tie_m.sum()),
            )
        afk |= bad | overflow | tie_m  # encode.py's anyafk |= bad semantics

        stream = MatchStream(
            player_idx=player_idx, winner=winner, mode_id=mode_id, afk=afk
        )

        # -- player state: NULL stays NaN ('nan' fill in the bulk scan) --
        table = np.full((p + 1, TABLE_WIDTH), np.nan, np.float32)
        rrk = np.full(p + 1, np.nan, np.float32)
        rbl = np.full(p + 1, np.nan, np.float32)
        tier = np.zeros(p + 1, np.int32)
        if "rank_points_ranked" in pl:
            rrk[:p] = pl["rank_points_ranked"].astype(np.float32)
        if "rank_points_blitz" in pl:
            rbl[:p] = pl["rank_points_blitz"].astype(np.float32)
        if "skill_tier" in pl:
            tier[:p] = np.clip(
                pl["skill_tier"],
                constants.MIN_SKILL_TIER, constants.MAX_SKILL_TIER,
            ).astype(np.int32)
        for c, base in enumerate(RATING_COLUMNS):
            for col, lo_ in ((f"{base}_mu", MU_LO), (f"{base}_sigma", SIGMA_LO)):
                if col in pl:
                    table[:p, lo_ + c] = pl[col].astype(np.float32)
        del pl
        seed_mu, seed_sigma = trueskill_seed_host(rrk, rbl, tier, cfg)
        table[:, COL_SEED_MU] = seed_mu
        table[:, COL_SEED_SIGMA] = seed_sigma
        state = PlayerState(
            table=jnp.asarray(table),
            rank_points_ranked=jnp.asarray(rrk),
            rank_points_blitz=jnp.asarray(rbl),
            skill_tier=jnp.asarray(tier),
            seed_cfg=cfg,
        )

        cur.close()
        self.conn.rollback()  # release the read snapshot (see asset_urls)
        return ColumnarHistory(
            stream=stream, state=state,
            match_ids=_decode_list(m_ids),
            player_ids=_decode_list(p_ids),
        )

    def write_players(self, state, player_ids: list) -> int:
        """Bulk write-back of the final rating table to the ``player``
        table (the persistence step of a ``rate --db`` full re-rate; the
        service path's per-batch ``commit`` is unchanged). Only rows with
        at least one rating are updated; columns the live schema lacks
        are dropped exactly like :meth:`commit`. Returns rows updated."""
        import numpy as np

        from analyzer_tpu.core.state import MU_LO, SIGMA_LO

        cols = self._rating_cols["player"]
        if not cols:
            return 0
        tbl = np.asarray(state.table)[: len(player_ids)]
        col_of = {name: i for i, name in enumerate(RATING_COLUMNS)}
        slices = [
            (MU_LO if c.endswith("_mu") else SIGMA_LO)
            + col_of[c.rsplit("_", 1)[0]]
            for c in cols
        ]
        rated = ~np.isnan(tbl[:, MU_LO])  # shared mu set => player touched
        idxs = np.flatnonzero(rated)
        if idxs.size == 0:
            return 0
        # Row building is vectorized: the per-element float()/isnan python
        # loop cost ~4 s at 333k players. float64 (a Python-float subclass
        # the DB-API binds natively; float32 is not) -> object array with
        # NaN -> None, ids appended as the last parameter column.
        vals = tbl[np.ix_(idxs, slices)].astype(np.float64)
        obj = vals.astype(object)
        obj[np.isnan(vals)] = None
        ids = np.array(player_ids, dtype=object)[idxs]
        rows = np.concatenate([obj, ids[:, None]], axis=1).tolist()
        mark = "?" if self._paramstyle == "qmark" else "%s"
        sql = (
            f"UPDATE {self._q('player')} SET "
            + ", ".join(f"{self._q(c)} = {mark}" for c in cols)
            + f" WHERE {self._q('api_id')} = {mark}"
        )
        try:
            cur = self.conn.cursor()
            cur.executemany(sql, rows)
            cur.close()
            self.conn.commit()
        except Exception:
            self.conn.rollback()
            raise
        return len(rows)

    def asset_urls(self, match_api_id: str) -> list[str]:
        rows = self._select_in("asset", ("url",), "match_api_id", [match_api_id])
        # Release the read snapshot the SELECT opened — the reference uses a
        # throwaway autocommit session here (worker.py:124-126); on MySQL a
        # lingering REPEATABLE READ snapshot would hide newly ingested rows
        # from the next load_batch. Never reached with writes pending: the
        # worker commits before fan-out. No-op on sqlite.
        self.conn.rollback()
        return [r[0] for r in rows]

    # -- transaction ------------------------------------------------------
    def commit(self, matches: list) -> None:
        """Flushes the batch graph's rating columns in one transaction
        (the reference's single ``db.commit()`` with rollback-and-reraise,
        ``worker.py:194-199``)."""
        try:
            cur = self.conn.cursor()
            mark = "?" if self._paramstyle == "qmark" else "%s"

            def update(table: str, cols: list[str], key: str, objs: list):
                # Filter against the live schema FIRST, then build rows —
                # columns the deployed schema lacks are dropped, exactly as
                # automap never flushes a non-column attribute.
                cols = [c for c in cols if c in self.columns[table]]
                if not objs or not cols:
                    return
                sql = (
                    f"UPDATE {self._q(table)} SET "
                    + ", ".join(f"{self._q(c)} = {mark}" for c in cols)
                    + f" WHERE {self._q(key)} = {mark}"
                )
                rows = [
                    tuple(getattr(o, c, None) for c in cols) + (getattr(o, key),)
                    for o in objs
                ]
                cur.executemany(sql, rows)

            parts = [p for m in matches for p in m.participants]
            players = {p.player[0].api_id: p.player[0] for p in parts}
            items = [it for p in parts for it in p.participant_items]

            update("match", ["trueskill_quality"], "api_id", matches)
            update("participant",
                   ["trueskill_mu", "trueskill_sigma", "trueskill_delta"],
                   "api_id", parts)
            update("player", self._rating_cols["player"], "api_id",
                   list(players.values()))
            update("participant_items",
                   ["any_afk"] + self._rating_cols["participant_items"],
                   "api_id", items)
            cur.close()
            self.conn.commit()
        except Exception:
            self.conn.rollback()
            raise

    def rollback(self) -> None:
        self.conn.rollback()

    def close(self) -> None:
        self.conn.close()
