"""SQL match store: the reference's reflected-MySQL layer on raw DB-API.

The reference's L2 is SQLAlchemy automap — the schema is *reflected at
runtime*, never declared in code (``worker.py:43-46``), then the match →
roster → participant → player / participant_items graph is eager-loaded
with ``selectinload`` and written back with one transaction per batch
(``worker.py:169-199``). This adapter keeps every one of those contracts on
plain DB-API 2.0 instead of SQLAlchemy (not installed in this image; an ORM
wrapper would be dead code the tests can never run — the fate the round-1
review flagged for the pika adapter):

  * runtime reflection — table/column sets are discovered from the live
    database (``PRAGMA table_info`` / ``SHOW COLUMNS``), so the loaded
    column set and the write-back column set adapt to the deployed schema
    exactly as automap does; rating columns the schema lacks are silently
    dropped at commit, which is literally automap's behavior (setattr of a
    non-column name is a plain Python attribute the ORM never flushes).
  * selectin eager loading — one query per relationship level keyed by the
    parent ids (``worker.py:176-191``'s ``selectinload`` chain), matches
    ordered by ``created_at`` ascending, ids deduped (``worker.py:172,176``).
  * single-transaction write-back — ``commit()`` flushes every rating
    column of the loaded graph with ``executemany`` and commits once;
    any error rolls back and re-raises (``worker.py:194-199``).
  * ``asset_urls`` — the telesuck query (``SELECT url FROM asset WHERE
    match_api_id = ?``, ``worker.py:150-153``), autocommit read like the
    reference's separate throwaway session (``worker.py:124-126``).

Drivers: ``sqlite://`` URIs use the stdlib ``sqlite3`` (what the tests
exercise end-to-end); ``mysql://`` URIs try the reference's cymysql pin
first (``requirements.txt:1``), then pymysql/MySQLdb — gated imports, same
policy as the pika broker adapter.

Loaded objects are ``types.SimpleNamespace`` graphs shaped exactly like the
parity-test fakes (``tests/fakes.py``; the reference's ``worker_test.py:6-63``
strategy), so the whole encode → rate → write_back path is indifferent to
whether a match came from SQL or memory.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Iterable
from urllib.parse import urlparse, unquote

from analyzer_tpu.core.constants import RATING_COLUMNS
from analyzer_tpu.logging_utils import get_logger

logger = get_logger(__name__)

# The de-facto feature schema of the rating path: the reference's load_only
# column lists (worker.py:176-191). 5v5 columns are absent there and filled
# by lazy loading at runtime in SQLAlchemy; here reflection adds whichever
# rating pairs the live schema actually has (an eager superset, documented
# divergence — there is no lazy loading without an ORM session).
MATCH_COLS = ("api_id", "game_mode", "created_at")
ROSTER_COLS = ("api_id", "match_api_id", "winner")
PARTICIPANT_COLS = (
    "api_id", "match_api_id", "roster_api_id",
    "player_api_id", "skill_tier", "went_afk",
)
PLAYER_BASE_COLS = ("api_id", "rank_points_ranked", "rank_points_blitz")

REQUIRED_TABLES = (
    "match", "asset", "roster", "participant", "participant_items", "player",
)


def _connect(uri: str):
    """Opens a DB-API connection + paramstyle marker for the URI."""
    parsed = urlparse(uri)
    scheme = parsed.scheme.split("+")[0]
    if scheme == "sqlite":
        import sqlite3

        # sqlite:///rel.db | sqlite:////abs.db | sqlite:// (in-memory).
        # A netloc (sqlite://host/x) is not a filesystem path — folding it
        # into one would silently open './host/x'; reject the unsupported
        # host form instead.
        if parsed.netloc:
            raise ValueError(
                f"sqlite URIs take no host: {uri!r} (use sqlite:///rel.db "
                "or sqlite:////abs.db)"
            )
        path = parsed.path or ""
        if path.startswith("/") and not path.startswith("//"):
            path = path[1:]
        elif path.startswith("//"):
            path = path[1:]
        conn = sqlite3.connect(path or ":memory:")
        return conn, "qmark", "sqlite"
    if scheme == "mysql":
        last: Exception | None = None
        for drv in ("cymysql", "pymysql", "MySQLdb"):
            try:
                mod = __import__(drv)
            except ImportError as err:  # gated like the pika adapter
                last = err
                continue
            conn = mod.connect(
                host=parsed.hostname or "localhost",
                port=parsed.port or 3306,
                user=unquote(parsed.username or ""),
                passwd=unquote(parsed.password or ""),
                db=parsed.path.lstrip("/"),
            )
            return conn, "format", "mysql"
        raise ImportError(
            f"no MySQL driver available for {uri!r} (tried cymysql, pymysql, "
            f"MySQLdb — the reference pins cymysql, requirements.txt:1): {last}"
        )
    raise ValueError(f"unsupported DATABASE_URI scheme: {parsed.scheme!r}")


class SqlStore:
    """Match store over a SQL database, satisfying the worker's store
    protocol (``load_batch``, ``asset_urls``) plus the transactional
    ``commit``/``rollback`` the reference performs per batch.

    ``chunk_size`` bounds per-query row batches (the IN-list split in
    ``_select_in``) — the DB-API analog of the reference's
    ``yield_per(CHUNKSIZE)`` row streaming (``worker.py:19,191``)."""

    def __init__(self, uri: str, chunk_size: int = 100) -> None:
        self.uri = uri
        self.chunk_size = max(int(chunk_size), 1)
        self.conn, self._paramstyle, self._dialect = _connect(uri)
        self.columns = self._reflect()
        missing = [t for t in REQUIRED_TABLES if t not in self.columns]
        if missing:
            raise RuntimeError(
                f"schema reflection: required tables missing from {uri!r}: "
                f"{missing} (the reference reflects match/asset/roster/"
                "participant/participant_stats/participant_items/player, "
                "worker.py:50-83)"
            )
        # participant_stats is reflected but never loaded nor written —
        # the reference wires it (worker.py:75-78) and never touches it.
        self._rating_cols = {
            table: [
                c
                for col in RATING_COLUMNS
                for c in (f"{col}_mu", f"{col}_sigma")
                if c in self.columns[table]
            ]
            for table in ("player", "participant_items")
        }

    # -- reflection -------------------------------------------------------
    def _reflect(self) -> dict[str, list[str]]:
        cur = self.conn.cursor()
        out: dict[str, list[str]] = {}
        if self._dialect == "sqlite":
            cur.execute(
                "SELECT name FROM sqlite_master WHERE type='table'"
            )
            tables = [r[0] for r in cur.fetchall()]
            for t in tables:
                cur.execute(f'PRAGMA table_info("{t}")')
                out[t] = [r[1] for r in cur.fetchall()]
        else:
            cur.execute("SHOW TABLES")
            tables = [r[0] for r in cur.fetchall()]
            for t in tables:
                cur.execute(f"SHOW COLUMNS FROM `{t}`")
                out[t] = [r[0] for r in cur.fetchall()]
        cur.close()
        return out

    # -- query helpers ----------------------------------------------------
    def _ph(self, n: int) -> str:
        mark = "?" if self._paramstyle == "qmark" else "%s"
        return ",".join([mark] * n)

    def _q(self, name: str) -> str:
        return f'"{name}"' if self._dialect == "sqlite" else f"`{name}`"

    def _select_in(self, table: str, cols: Iterable[str], key: str,
                   values: list, order_by: str | None = None) -> list[tuple]:
        if not values:
            return []
        cols = list(cols)
        cur = self.conn.cursor()
        # Chunk the IN list (the reference bounds per-query row streaming
        # with yield_per(CHUNKSIZE), worker.py:19,191; huge IN lists are
        # the DB-API analog of that concern).
        step = self.chunk_size
        rows: list[tuple] = []
        for i in range(0, len(values), step):
            chunk = values[i : i + step]
            sql = (
                f"SELECT {', '.join(self._q(c) for c in cols)} "
                f"FROM {self._q(table)} "
                f"WHERE {self._q(key)} IN ({self._ph(len(chunk))})"
            )
            if order_by:
                sql += f" ORDER BY {self._q(order_by)} ASC"
            cur.execute(sql, chunk)
            rows.extend(cur.fetchall())
        cur.close()
        if order_by and len(values) > step:
            idx = cols.index(order_by)
            # NULL-safe merge of the per-chunk ORDER BYs: None cannot be
            # compared to str/datetime in python; sqlite sorts NULLs
            # first, so mirror that.
            # Tuple keys never compare the second element across the
            # None/non-None boundary (the bool decides), and equal Nones
            # need no ordering call.
            rows.sort(key=lambda r: (r[idx] is not None, r[idx]))
        return rows

    # -- store protocol ---------------------------------------------------
    def load_batch(self, ids: Iterable[str]) -> list:
        """Dedupe + load the eager object graph, matches ordered by
        ``created_at`` ascending (``worker.py:172,176-191``)."""
        seen = list(dict.fromkeys(ids))
        match_rows = self._select_in(
            "match", MATCH_COLS, "api_id", seen, order_by="created_at"
        )
        matches: list[SimpleNamespace] = []
        mids = []
        for api_id, game_mode, created_at in match_rows:
            m = SimpleNamespace(
                api_id=api_id, game_mode=game_mode, created_at=created_at,
                trueskill_quality=None, rosters=[], participants=[],
            )
            matches.append(m)
            mids.append(api_id)

        # selectin level 1: rosters of the batch's matches
        by_match: dict[str, SimpleNamespace] = {m.api_id: m for m in matches}
        rosters: dict[str, SimpleNamespace] = {}
        for api_id, match_api_id, winner in self._select_in(
            "roster", ROSTER_COLS, "match_api_id", mids
        ):
            r = SimpleNamespace(
                api_id=api_id, match_api_id=match_api_id, winner=winner,
                participants=[],
            )
            rosters[api_id] = r
            by_match[match_api_id].rosters.append(r)

        # selectin level 2: participants (keyed by match, attached to both
        # match.participants and roster.participants like the double
        # relationship wiring at worker.py:52-66)
        part_rows = self._select_in(
            "participant", PARTICIPANT_COLS, "match_api_id", mids
        )
        player_ids = list(dict.fromkeys(r[3] for r in part_rows))
        # selectin level 3: players, full reflected rating column set.
        # player.skill_tier is not in the reference's load_only list
        # (worker.py:184-190) but get_trueskill_seed reads it lazily
        # (rater.py:57-60); reflection loads it eagerly when it exists.
        player_cols = list(PLAYER_BASE_COLS) + self._rating_cols["player"]
        if "skill_tier" in self.columns["player"]:
            player_cols.insert(len(PLAYER_BASE_COLS), "skill_tier")
        players: dict[str, SimpleNamespace] = {}
        for row in self._select_in("player", player_cols, "api_id", player_ids):
            p = SimpleNamespace(**dict(zip(player_cols, row)))
            if not hasattr(p, "skill_tier"):
                p.skill_tier = None
            for col in RATING_COLUMNS:  # absent schema columns read as None
                for c in (f"{col}_mu", f"{col}_sigma"):
                    if not hasattr(p, c):
                        setattr(p, c, None)
            players[p.api_id] = p

        # selectin level 3b: participant_items rows
        items_cols = ["api_id", "participant_api_id", "any_afk"]
        items_cols += self._rating_cols["participant_items"]
        items_by_part: dict[str, list[SimpleNamespace]] = {}
        part_ids = [r[0] for r in part_rows]
        for row in self._select_in(
            "participant_items", items_cols, "participant_api_id", part_ids
        ):
            it = SimpleNamespace(**dict(zip(items_cols, row)))
            for col in RATING_COLUMNS[1:]:
                for c in (f"{col}_mu", f"{col}_sigma"):
                    if not hasattr(it, c):
                        setattr(it, c, None)
            items_by_part.setdefault(it.participant_api_id, []).append(it)

        for api_id, match_api_id, roster_api_id, player_api_id, skill_tier, went_afk in part_rows:
            part = SimpleNamespace(
                api_id=api_id,
                match_api_id=match_api_id,
                roster_api_id=roster_api_id,
                player_api_id=player_api_id,
                skill_tier=skill_tier,
                went_afk=went_afk,
                trueskill_mu=None,
                trueskill_sigma=None,
                trueskill_delta=None,
                player=[players[player_api_id]],
                participant_items=items_by_part.get(api_id, []),
            )
            by_match[match_api_id].participants.append(part)
            if roster_api_id in rosters:
                rosters[roster_api_id].participants.append(part)
        return matches

    def asset_urls(self, match_api_id: str) -> list[str]:
        rows = self._select_in("asset", ("url",), "match_api_id", [match_api_id])
        # Release the read snapshot the SELECT opened — the reference uses a
        # throwaway autocommit session here (worker.py:124-126); on MySQL a
        # lingering REPEATABLE READ snapshot would hide newly ingested rows
        # from the next load_batch. Never reached with writes pending: the
        # worker commits before fan-out. No-op on sqlite.
        self.conn.rollback()
        return [r[0] for r in rows]

    # -- transaction ------------------------------------------------------
    def commit(self, matches: list) -> None:
        """Flushes the batch graph's rating columns in one transaction
        (the reference's single ``db.commit()`` with rollback-and-reraise,
        ``worker.py:194-199``)."""
        try:
            cur = self.conn.cursor()
            mark = "?" if self._paramstyle == "qmark" else "%s"

            def update(table: str, cols: list[str], key: str, objs: list):
                # Filter against the live schema FIRST, then build rows —
                # columns the deployed schema lacks are dropped, exactly as
                # automap never flushes a non-column attribute.
                cols = [c for c in cols if c in self.columns[table]]
                if not objs or not cols:
                    return
                sql = (
                    f"UPDATE {self._q(table)} SET "
                    + ", ".join(f"{self._q(c)} = {mark}" for c in cols)
                    + f" WHERE {self._q(key)} = {mark}"
                )
                rows = [
                    tuple(getattr(o, c, None) for c in cols) + (getattr(o, key),)
                    for o in objs
                ]
                cur.executemany(sql, rows)

            parts = [p for m in matches for p in m.participants]
            players = {p.player[0].api_id: p.player[0] for p in parts}
            items = [it for p in parts for it in p.participant_items]

            update("match", ["trueskill_quality"], "api_id", matches)
            update("participant",
                   ["trueskill_mu", "trueskill_sigma", "trueskill_delta"],
                   "api_id", parts)
            update("player", self._rating_cols["player"], "api_id",
                   list(players.values()))
            update("participant_items",
                   ["any_afk"] + self._rating_cols["participant_items"],
                   "api_id", items)
            cur.close()
            self.conn.commit()
        except Exception:
            self.conn.rollback()
            raise

    def rollback(self) -> None:
        self.conn.rollback()

    def close(self) -> None:
        self.conn.close()
