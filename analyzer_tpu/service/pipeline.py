"""Pipelined service loop: overlap host work with the device round trip.

The sequential worker (``Worker.process``) is the reference's shape —
load, encode, rate, write back, commit, one batch at a time
(``/root/reference/worker.py:95-199``). On this rig the device round trip
(the packed-outputs D2H fetch crossing the tunnel, ~100-150 ms) dominates
each 500-match batch, and the sequential loop spends it idle. This engine
keeps the per-batch failure policy while hiding the fetch behind the NEXT
batch's host work:

  * **Device-side prior chaining** breaks the fetch -> encode dependency.
    Batch N+1's priors normally come from the store, which doesn't have
    batch N's posteriors until N's outputs are fetched and committed.
    Instead, N+1 is encoded from a (stale-by-<=lag) store snapshot and its
    player table is PATCHED ON DEVICE from the final device-resident
    tables of the in-flight batches, held in a ``[lag, rows, W]`` ring:
    ONE jitted call applies the whole chain
    (``_chain_patch_pairs``), keyed by player-id overlap computed on the
    host from the encoders' ``row_of`` maps. Only the 14 rating columns
    copy — seeds derive from static features the worker never writes,
    and the destination batch's are fresher. The posterior never visits
    the host on the critical path.
  * **Async D2H at dispatch**: each batch's packed-outputs transfer is
    issued (``copy_to_host_async``) the moment its scan is enqueued, so
    by the time the ordered writer materializes it the bytes have been
    streaming for ~lag batch periods. (A fetch THREAD POOL measured
    strictly worse: tunnel + GIL contention with encode/write_back.)
  * **An ordered writer thread** applies ``write_back`` + ``commit``
    strictly in batch order (players are shared across batches — the
    last-write-wins order must match the sequential loop) on its OWN
    store handle (``SqlStore.clone``; sqlite connections are bound to
    their creating thread).
  * **Main-thread harvest**: acks, notify/crunch/sew/telesuck fan-out,
    dead-lettering and failure fallback all stay on the consumer thread —
    the broker (pika especially) is not thread-safe.

Correctness argument (the induction ``tests/test_pipeline.py`` pins):

  With commit lag ``L``, a batch's store load happens only after batch
  ``N-L`` committed (the submit gate), so its snapshot is missing at most
  the writes of batches ``N-L+1..N`` — exactly the ones patched, in
  order, from their device-resident final tables. Patching from an
  already-committed batch is idempotent (the snapshot and the device
  table agree), so no per-batch commit bookkeeping is needed on the
  chaining side. Final ratings are bit-identical to the sequential loop.

Failure policy (``worker.py:110-120`` semantics preserved):

  The writer processes batches in order; the FIRST failure poisons the
  stream. The failed batch surfaces to the worker's normal failure
  handler (dead-letter + nack after rollback); every later in-flight
  batch is ABORTED — its device results are discarded (they chained off
  uncommitted state the sequential loop would never have seen) and its
  messages are reprocessed from scratch through the sequential path
  against the rolled-back store. A failed batch therefore never acks
  later batches, and an aborted batch never commits tainted state.

Semantic caveats vs the strictly sequential loop (documented, tested
where cheap):

  * The reference's out-of-table skill-tier KeyError consults "has a
    shared rating yet?" (``rater.py:57-60``); under chaining that check
    runs against the stale snapshot. A PoisonError raised during a
    pipelined encode is therefore retried ONCE from fully-drained
    committed state before the worker's poison isolation path engages.
  * Static seed features (rank_points/skill_tier) are read at load time;
    a concurrent external writer changing them can land one batch later
    than in the sequential loop — the reference has the same race across
    its competing consumers (SURVEY.md section 3.2).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from concurrent.futures import Future
from functools import partial

import jax
import numpy as np

from analyzer_tpu.core.state import MU_LO, SIGMA_HI
from analyzer_tpu.lint.ownership import thread_role
from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs import get_flight_recorder, get_registry, get_tracer
from analyzer_tpu.obs.tracer import bind_trace, current_trace
from analyzer_tpu.sched.runner import _gather_outputs, _scan_chunk
from analyzer_tpu.service.columnar import finalize
from analyzer_tpu.utils.host import fetch_tree

logger = get_logger(__name__)

# Fallback commit lag when nothing was measured (engine constructed
# without a warmup probe and without an explicit PIPELINE_LAG): the
# round-4 A/B winner on the tunneled dev rig (~100-200 ms RTT vs ~45 ms
# host work -> choose_pipeline_lag lands on 6 there too).
DEFAULT_LAG = 6


def choose_pipeline_lag(rtt_s: float, host_s: float) -> int:
    """Commit lag from measured costs: enough in-flight batches that the
    dispatch->fetch round trip hides entirely behind host work.

    Steady state, one batch period ~= max(host_s, device_s): the fetch
    issued at batch N's dispatch must complete before the writer needs it,
    i.e. within ``lag`` batch periods — ``lag >= rtt / host`` — plus one
    period of slack for jitter (the tunnel's RTT spread is the dominant
    variance on this rig). Clamped: the floor keeps one full RTT
    overlapped even when host work dominates (a real TPU host at ~1 ms
    dispatch wants the floor, not the tunnel's 6); the ceiling bounds the
    failure blast radius and the unacked-message window
    (``ServiceConfig.prefetch_count``)."""
    from analyzer_tpu.config import PIPELINE_MAX_LAG, PIPELINE_MIN_LAG

    if host_s <= 0:
        return PIPELINE_MAX_LAG
    lag = -(-rtt_s // host_s) + 1  # ceil + jitter slack
    return int(min(PIPELINE_MAX_LAG, max(PIPELINE_MIN_LAG, lag)))


class PipelineFallback(Exception):
    """Submit could not take the batch; the worker must harvest (to apply
    the pending failure policy) and run the batch sequentially."""


@partial(jax.jit, static_argnames=("rows",))
def _canonical_rows(table, rows: int):
    """Zero-pads a final batch table to the worker's MAX row bucket.
    Chain sources are canonicalized ONCE per batch so ``_chain_patch_pairs``
    compiles per destination rung only — without this, mixed-size
    batch successions (a full batch after an idle flush) would compile
    every (dst_rows, src_rows) PAIR in the ladder (64 shapes at
    BATCHSIZE=500 instead of 2x8, unwarmable in practice)."""
    return jax.numpy.pad(table, ((0, rows - table.shape[0]), (0, 0)))


@partial(jax.jit, donate_argnums=(0,))
def _ring_put(ring, slot, table):
    """Writes one canonicalized batch table into the chain ring."""
    return ring.at[slot].set(table)


def pair_index_dtype(canon_rows: int):
    """int16 halves the per-batch pair upload; row/pad indices only
    exceed it under a far-over-default BATCHSIZE."""
    return np.int16 if canon_rows <= 32000 else np.int32


def chain_buffers(lag: int, canon_rows: int):
    """(ring, pairs, pair_dtype) for a chain of depth ``lag`` over
    ``canon_rows``-row canonical tables — the ONE owner of the ring
    shape and the pair index dtype, shared by ``Worker.warmup`` (which
    must compile exactly the shapes production hits) and
    ``PipelineEngine`` (which runs them)."""
    import jax.numpy as jnp

    from analyzer_tpu.core.state import TABLE_WIDTH

    dtype = pair_index_dtype(canon_rows)
    ring = jnp.zeros((lag, canon_rows, TABLE_WIDTH), jnp.float32)
    pairs = jnp.zeros((3, canon_rows), dtype)
    return ring, pairs, dtype


@partial(jax.jit, donate_argnums=(0,))
def _chain_patch_pairs(dst_table, ring, pairs):
    """Applies the WHOLE chain in one dispatch from compacted pairs:
    ``pairs`` is ``[3, K]`` (ring slot, ring row, destination row), one
    gather + one scatter. Padding entries point their destination at the
    table's padding row, where writes park like every masked scatter in
    the framework (the pad row's value is garbage by design, so the
    duplicate pad writes' ordering is irrelevant); NON-pad destinations
    are UNIQUE by construction — the host deduplicates newest-entry-wins
    (chain_pairs), which also preserves the sequential oldest-first
    patch order's final values without any in-kernel ordering.

    Why pairs and not a dense [lag, rows] index grid: the grid's H2D
    upload scales with lag (lag 12 = ~390 KB/batch), and the tunneled
    dev rig's ~3 MB/s H2D made deep commit lags collapse (~130 ms/batch
    of index upload alone — measured round 5: lag 12 ran at 1.4-1.5k
    matches/s under BOTH the per-entry and dense-grid designs). The
    compact form is lag-independent (~48 KB at the service default)."""
    slots = pairs[0].astype(jax.numpy.int32)
    srcs = pairs[1].astype(jax.numpy.int32)
    dsts = pairs[2].astype(jax.numpy.int32)
    vals = ring[slots, srcs, MU_LO:SIGMA_HI]
    return dst_table.at[dsts, MU_LO:SIGMA_HI].set(vals)


def chain_pairs(chain, lag: int, dst_row_of: dict, dst_pad_row: int,
                canon_rows: int, dtype) -> np.ndarray:
    """Host half of the ring patch: ``[3, canon_rows]`` (slot, src row,
    dst row) with newest-first dedup per destination — the final value
    of applying the chain oldest-first is exactly the newest in-flight
    batch's row for each overlapping player. Unused capacity points at
    the destination padding row."""
    pairs = np.zeros((3, canon_rows), dtype)
    pairs[2, :] = dst_pad_row
    seen: set = set()
    n = 0
    for seq_e, row_of in reversed(chain):  # newest first
        slot = seq_e % lag
        for pid, r in row_of.items():
            d = dst_row_of.get(pid)
            if d is not None and d not in seen:
                seen.add(d)
                pairs[0, n] = slot
                pairs[1, n] = r
                pairs[2, n] = d
                n += 1
    return pairs


class _LazyFetch:
    """Future-shaped handle that materializes the packed outputs on the
    CALLING (writer) thread. The D2H transfer was issued at dispatch via
    ``copy_to_host_async`` — ``result()`` mostly just wraps the already-
    arrived bytes into stream-ordered HistoryOutputs."""

    def __init__(self, ys_chunks, flat_idx, n, team):
        self._args = (ys_chunks, flat_idx, n, team)

    def result(self):
        ys_chunks, flat_idx, n, team = self._args
        return _gather_outputs(
            [fetch_tree(ys) for ys in ys_chunks], flat_idx, n, team
        )


class _EmptyBatch:
    """Stand-in EncodedBatch for a batch whose ids loaded no matches —
    the reference's query returns no rows and the messages fall straight
    through to the ack loop (``worker.py:122-129``)."""

    matches: list = []

    def write_back(self, outs) -> None:  # pragma: no cover — trivial
        pass


@dataclasses.dataclass
class _Job:
    seq: int
    msgs: list
    enc: object  # EncodedBatch (or _EmptyBatch)
    fetch: Future  # -> HistoryOutputs (or None for _EmptyBatch)
    status: str = "inflight"  # -> ok | failed | aborted
    error: BaseException | None = None
    # The batch's FINAL device table (serve-plane publish source), held
    # only when the worker runs a ratings view. Published at harvest —
    # strictly AFTER the writer committed — so readers never see a
    # posterior the store might still roll back.
    view_table: object = None
    # Causal-trace id of the batch (None when tracing is off): the
    # writer thread re-binds it so batch.fetch/batch.write_back join
    # the batch's tree, and harvest re-binds it around publish + ack.
    trace: str | None = None


class _Writer(threading.Thread):
    """Applies write_back + commit strictly in submit order on its own
    store handle. The first failure poisons the stream: every later job
    is aborted untouched (the worker reprocesses its messages)."""

    def __init__(self, store_factory) -> None:
        super().__init__(daemon=True, name="analyzer-pipeline-writer")
        # The store handle is created ON this thread (run()): sqlite
        # connections may only be used by their creating thread.
        self._store_factory = store_factory
        self.store = None
        self.jobs: deque[_Job] = deque()
        self.done: deque[_Job] = deque()
        self.cv = threading.Condition()
        self.left_seq = -1  # highest seq that has LEFT the writer
        self.poisoned = False
        self._active = False
        self._stop_requested = False

    @thread_role("any")
    def submit(self, job: _Job) -> None:
        with self.cv:
            self.jobs.append(job)
            self.cv.notify_all()

    @thread_role("any")
    def stop(self) -> None:
        with self.cv:
            self._stop_requested = True
            self.cv.notify_all()

    @thread_role("any")
    def wait_left(self, seq: int) -> bool:
        """Blocks until every job with ``seq' <= seq`` has left the
        writer (ok OR aborted). Returns False when the stream is
        poisoned OR the writer thread is dead (jobs can never leave a
        dead writer — without the liveness check this gate would hang
        the consumer forever) — either way the caller must go through
        harvest, which aborts stranded jobs for sequential
        reprocessing."""
        with self.cv:
            while self.left_seq < seq and not self.poisoned:
                if not self.is_alive():
                    return False
                self.cv.wait(0.1)
            return not self.poisoned

    @thread_role("any")
    def wait_idle(self) -> None:
        """Blocks until the queue is empty and nothing is mid-flight.
        Used by harvest after a failure: every queued job drains to
        ``done`` as aborted before the reset. A dead writer (store
        factory failure) can't drain — its stranded jobs are aborted
        here so the worker reprocesses their messages."""
        with self.cv:
            while self.jobs or self._active:
                if not self.is_alive():
                    while self.jobs:
                        job = self.jobs.popleft()
                        job.status = "aborted"
                        self.done.append(job)
                    self._active = False
                    break
                self.cv.wait(0.1)

    @thread_role("consumer")
    def run(self) -> None:
        try:
            self.store = self._store_factory()
        except Exception:
            # A dead writer must not hang every gate wait: poison the
            # stream so submit falls back to the sequential loop.
            logger.exception("pipeline writer store unavailable")
            get_flight_recorder().note("pipeline.writer_dead",
                                       why="store factory failed")
            with self.cv:
                self.poisoned = True
                self.cv.notify_all()
            return
        while True:
            with self.cv:
                while not self.jobs and not self._stop_requested:
                    self.cv.wait()
                if not self.jobs:
                    return  # stop requested, queue drained
                job = self.jobs.popleft()
                self._active = True
                poisoned = self.poisoned
            if poisoned:
                job.status = "aborted"
            else:
                try:
                    # Two spans, not one: fetch materializes the async D2H
                    # stream (tunnel-bound), write_back+commit is store
                    # work — the split is exactly the balance the lag
                    # auto-tuner reasons about (choose_pipeline_lag). The
                    # job's batch trace re-binds here so both spans join
                    # the consumer thread's tree (bind is a no-op when
                    # tracing was off at submit).
                    with bind_trace(job.trace):
                        with get_tracer().span(
                            "batch.fetch", cat="pipeline", seq=job.seq
                        ):
                            outs = job.fetch.result()
                        with get_tracer().span(
                            "batch.write_back", cat="pipeline", seq=job.seq
                        ):
                            finalize(self.store, job.enc, outs)
                    job.status = "ok"
                except BaseException as err:  # noqa: BLE001 — policy boundary
                    job.status = "failed"
                    job.error = err
                    # Breadcrumb BEFORE the worker's harvest dumps the
                    # flight artifact: the writer thread is where the
                    # failure actually happened, and events.log should
                    # carry its seq + error next to the fetch spans.
                    get_flight_recorder().note(
                        "pipeline.writer_failure",
                        seq=job.seq, error=repr(err),
                    )
                    rollback = getattr(self.store, "rollback", None)
                    if rollback is not None:
                        try:
                            rollback()
                        except Exception:  # pragma: no cover — best effort
                            logger.exception("writer rollback failed")
            with self.cv:
                self.done.append(job)
                self._active = False
                if job.status == "failed":
                    self.poisoned = True
                else:
                    self.left_seq = job.seq
                self.cv.notify_all()


class PipelineEngine:
    """Drives the pipelined batch flow for a :class:`Worker`.

    The worker owns the broker and the failure policy; the engine owns
    dispatch ordering, the chaining state, the fetch pool and the writer.
    ``lag`` = max batches in flight past the last known commit. ``None``
    resolves from the worker's warmup-measured dispatch->fetch RTT and
    per-batch host time (:func:`choose_pipeline_lag`), else
    :data:`DEFAULT_LAG`; production passes ``ServiceConfig.pipeline_lag``
    (default None = auto, ``PIPELINE_LAG`` pins it). 1 degrades toward
    the sequential loop.
    """

    def __init__(self, worker, lag: int | None = None):
        self.worker = worker
        if lag is None:
            lag = worker.resolved_pipeline_lag()
        self.lag = max(1, int(lag))
        get_registry().gauge("worker.pipeline_lag").set(self.lag)
        store = worker.store
        clone = getattr(store, "clone", None)
        if clone is not None:
            clone().close()  # eager validation on the consumer thread:
            # an uncloneable store (in-memory sqlite) raises HERE, where
            # the worker can fall back to the sequential loop — not
            # asynchronously on the writer.
            factory = clone
        else:
            factory = lambda: store  # noqa: E731 — shared-object stores
        self.writer = _Writer(factory)
        self.writer.start()
        # Chaining sources: (seq, row_of) of the last `lag` dispatched
        # batches, newest last. The batches' canonicalized final tables
        # live DEVICE-SIDE in a [lag, canon_rows, W] ring (slot =
        # seq % lag), so the whole chain applies in one dispatch
        # (_chain_patch_pairs) instead of one per entry.
        self.chain: deque = deque(maxlen=self.lag)
        self._ring = None  # lazy: created at the first ringable batch
        self.seq = 0
        # One owner for the compile-shape knobs: the worker (warmup and
        # schedule bucketing read the same attributes).
        self._canon_rows = worker._canon_rows
        self._pair_dtype = pair_index_dtype(self._canon_rows)

    # -- submission -------------------------------------------------------
    def submit(self, msgs: list) -> None:
        """Dispatches one message batch into the pipeline.

        Raises :class:`PipelineFallback` when the pipeline is poisoned
        (harvest must apply the failure policy first), or lets a
        PoisonError propagate after the drained retry (the worker's
        isolation path takes over)."""
        from analyzer_tpu.service.encode import PoisonError

        w = self.worker
        # Gate: the store snapshot below must include every commit up to
        # seq - lag, so at most `lag` uncommitted batches need chaining.
        # The liveness check runs even when no waiting is needed — an
        # early-lag gate passes trivially, and enqueuing to a dead
        # writer would strand the batch's messages unacked forever.
        if not self.writer.is_alive() or not self.writer.wait_left(
            self.seq - self.lag
        ):
            raise PipelineFallback("pipeline poisoned or writer dead; "
                                   "harvest first")
        ids = [m.body.decode() for m in msgs]
        try:
            enc = self._encode_fresh(ids)
        except PoisonError:
            # The stale snapshot can mis-decide the reference's
            # seed-consulted KeyError gate (module docstring); retry once
            # from fully committed state before isolating.
            self.drain()
            if not self.worker.pipeline_enabled or not self.writer.is_alive():
                # The drain's harvest disabled the pipeline (dead
                # writer): this engine is orphaned — enqueuing to it
                # would strand the batch's messages unacked forever.
                raise PipelineFallback("pipeline disabled during drain")
            enc = self._encode_fresh(ids)
        n = len(enc.matches) if enc is not None else 0
        logger.info("processing batch of %s matches (pipelined)", n)
        if not n:
            self._enqueue(msgs, _EmptyBatch(), _done_future(None))
            return
        tracer = get_tracer()
        with tracer.span("batch.pack", cat="pipeline", matches=n):
            sched = w._bucketed_schedule(enc.stream, enc.state.pad_row)

        state = enc.state
        if self.chain:
            with tracer.span(
                "batch.chain", cat="pipeline", depth=len(self.chain)
            ):
                pairs = chain_pairs(
                    self.chain, self.lag, enc.row_of, enc.state.pad_row,
                    self._canon_rows, self._pair_dtype,
                )
                state = dataclasses.replace(
                    state,
                    table=_chain_patch_pairs(
                        state.table, self._ring, jax.numpy.asarray(pairs)
                    ),
                )
        # Chunked dispatch at the fixed service step shape (the schedule
        # is padded to a SERVICE_STEP_CHUNK multiple): any chain depth
        # reuses the one warmed compile per row bucket. The span measures
        # ENQUEUE cost only — jax dispatch is async by design; device
        # completion lands in the writer's batch.fetch span.
        dispatch_span = tracer.span(
            "batch.dispatch", cat="pipeline", seq=self.seq, matches=n,
            steps=sched.n_steps,
        )
        chunk = w._step_chunk
        ys_chunks = []
        with dispatch_span, w.profiler.maybe_capture(
            context={"matches": n, "steps": sched.n_steps, "seq": self.seq}
        ):
            for s0 in range(0, sched.n_steps, chunk):
                arrays = sched.device_arrays(s0, s0 + chunk)
                state, ys = _scan_chunk(state, arrays, w.rating_config, True,
                                        sched.pad_row)
                try:
                    # Start the D2H stream NOW (enqueued behind the scan):
                    # by the time the writer needs the outputs, the
                    # transfer has been in flight for ~lag batch periods
                    # instead of starting cold — measured on the tunneled
                    # v5e, this is what actually pipelines the per-batch
                    # RTT. The writer then materializes the already-
                    # streamed bytes; a fetch THREAD POOL measured
                    # strictly worse here (3 threads x np.asarray
                    # contending on the tunnel + GIL ping-pong with
                    # encode/write_back).
                    ys.copy_to_host_async()
                except AttributeError:  # pragma: no cover — older jax arrays
                    pass
                ys_chunks.append(ys)
        final = state
        flat_idx = sched.match_idx.reshape(-1)
        fetch = _LazyFetch(
            ys_chunks, flat_idx, sched.n_matches, sched.team_size
        )
        view_table = (
            final.table if w.view_publisher is not None else None
        )
        rows = int(final.table.shape[0])
        if rows <= self._canon_rows:
            if self._ring is None:
                self._ring, _, _ = chain_buffers(self.lag, self._canon_rows)
            self._ring = _ring_put(
                self._ring, self.seq % self.lag,
                _canonical_rows(final.table, self._canon_rows),
            )
            self.chain.append((self.seq, enc.row_of))
            self._enqueue(msgs, enc, fetch, view_table)
        else:
            # Defensive only — canon_rows is sized for the largest batch
            # the config can produce, so an over-bucket batch means the
            # sizing contract broke. It cannot ride the fixed-shape
            # ring; enqueue, then DRAIN so no later batch needs to chain
            # off it (one sequentialized batch, correctness intact).
            self._enqueue(msgs, enc, fetch, view_table)
            self.drain()

    def _encode_fresh(self, ids: list):
        """Load + encode (``Worker._encode_batch``, either lane) with the
        read-snapshot release. The consumer connection never commits in
        pipelined mode (the writer's clone does), so on MySQL a
        REPEATABLE READ snapshot pinned at the first SELECT would make
        every later load stale beyond the chain's ``lag`` window — the
        gate invariant requires each load to see commits up to
        ``seq - lag``. Rolling back after the rows are materialized
        forces the NEXT load to open a fresh snapshot (the same move
        ``asset_urls`` / ``_dead_letter`` make; no-op on sqlite). The
        rollback runs even when encode raises (poison) — the retry path
        must reload from a fresh snapshot too."""
        try:
            with get_tracer().span(
                "batch.encode", cat="pipeline", ids=len(ids)
            ):
                return self.worker._encode_batch(ids)
        finally:
            rollback = getattr(self.worker.store, "rollback", None)
            if rollback is not None:
                rollback()

    def _enqueue(
        self, msgs: list, enc, fetch: Future, view_table=None
    ) -> None:
        self.writer.submit(_Job(
            seq=self.seq, msgs=msgs, enc=enc, fetch=fetch,
            view_table=view_table,
            # Submit runs on the consumer thread inside the batch's
            # bind (Worker.try_process); capture it for the writer.
            trace=current_trace(),
        ))
        self.seq += 1
        self._update_inflight()

    def _update_inflight(self) -> None:
        """Pipeline-depth gauge: submitted batches not yet past the
        writer (the lag the chain ring is hiding right now)."""
        with self.writer.cv:
            left = self.writer.left_seq
        get_registry().gauge("worker.pipeline_inflight").set(
            max(0, self.seq - 1 - left)
        )

    # -- completion -------------------------------------------------------
    def harvest(self) -> None:
        """Applies completed jobs in order ON THE CONSUMER THREAD: acks +
        fan-out for successes, the worker's failure policy for the first
        failure, sequential reprocessing for aborted followers."""
        w = self.worker
        if not self.writer.is_alive():
            self.writer.wait_idle()  # recover jobs stranded by a dead writer
            # A dead writer never produces a `failed` job to reset the
            # poison (or to advance left_seq at all), so without this
            # every later flush would pay PipelineFallback + sequential
            # reprocessing forever — or hang on the submit gate.
            self.chain.clear()
            w._disable_pipeline("pipeline writer died")
        jobs = self._pop_done()
        if any(j.status == "failed" for j in jobs):
            # Every not-yet-processed job drains to `done` as aborted
            # before the reset — the poison flag must outlive them.
            self.writer.wait_idle()
            jobs += self._pop_done()
        reprocess: list[_Job] = []
        for job in jobs:
            if job.status == "ok":
                w.matches_rated += len(job.enc.matches)
                w.batches_ok += 1
                with bind_trace(job.trace):
                    if job.view_table is not None:
                        # Commit is durable (the writer finished this
                        # job): publish the batch's posteriors to the
                        # read plane before acking, mirroring the
                        # sequential lane's commit -> publish -> ack
                        # order. The bind makes the view.publish
                        # instant name this batch's trace.
                        w._publish_view(job.enc, job.view_table)
                    w._ack_batch(job.msgs)
            elif job.status == "failed":
                logger.error("pipelined batch failed: %s", job.error)
                w.batches_failed += 1
                w._dead_letter(job.msgs)
                # Chain state is tainted; the writer queue is empty
                # (wait_idle above), so the stream can restart cleanly.
                self.chain.clear()
                with self.writer.cv:
                    self.writer.poisoned = False
                    self.writer.left_seq = self.seq - 1
                    self.writer.cv.notify_all()
            else:  # aborted — chained off the failed batch; redo fresh
                reprocess.append(job)
        for job in sorted(reprocess, key=lambda j: j.seq):
            w._process_batch_sequential(job.msgs)
        self._update_inflight()

    def _pop_done(self) -> list:
        with self.writer.cv:
            jobs = sorted(self.writer.done, key=lambda j: j.seq)
            self.writer.done.clear()
        return jobs

    def drain(self) -> None:
        """Blocks until every submitted batch has left the writer, then
        harvests. Afterwards the store reflects every submitted batch (or
        its failure policy has been applied).

        The chain MUST clear here: callers commit through the store after
        a drain (sequential fallback, poison isolation), and a commit the
        chain never saw breaks patch idempotence — a later submit would
        overwrite those fresher rows with the chain's older device
        tables. Post-drain, a fresh load sees everything anyway."""
        self.writer.wait_left(self.seq - 1)  # False on poison: fall through
        self.writer.wait_idle()
        self.harvest()
        self.chain.clear()

    @property
    def idle(self) -> bool:
        with self.writer.cv:
            return (not self.writer.jobs and not self.writer.done
                    and not self.writer._active)

    def close(self) -> None:
        self.drain()
        self.writer.stop()
        self.writer.join(timeout=10)


def _done_future(value) -> Future:
    f: Future = Future()
    f.set_result(value)
    return f
