"""The worker: consume match ids, rate in batches, commit, fan out.

Mirrors the reference's control flow (``worker.py:95-166``) with the
vectorized rating path swapped in:

  * micro-batcher — accumulate messages; flush at ``batch_size`` or after
    ``idle_timeout`` seconds from the first queued message
    (``worker.py:95-101``);
  * process — dedupe ids, load chronologically, encode to tensors, run the
    conflict-free scheduler + jitted kernel, write back
    (``worker.py:169-199``; outputs are fully computed before any mutation,
    giving the reference's single-transaction semantics by construction);
  * failure policy — any exception dead-letters the WHOLE batch to
    ``<queue>_failed`` and nacks without requeue (``worker.py:110-120``);
  * fan-out — per-message ack; notify via topic exchange with the message's
    ``notify`` header; optional crunch/sew forwards of the raw body;
    optional telesuck publish of each telemetry URL with a
    ``match_api_id`` header (``worker.py:122-166``);
  * metrics — matches/sec counter, the BASELINE.json first-class output
    (SURVEY.md section 5.5: the reference has only debug logs);
  * pipelined mode (``service/pipeline.py``, on by default via env config,
    off for direct construction) — overlaps each batch's device round
    trip with the next batch's load/encode by chaining priors on device;
    measured 2.1x the sequential loop on this rig, bit-identical results,
    same failure policy.
"""

from __future__ import annotations

import dataclasses
import os
import time

from analyzer_tpu.config import RatingConfig, ServiceConfig
from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs import (
    get_device_profiler,
    get_flight_recorder,
    get_registry,
    get_tracer,
)
from analyzer_tpu.obs import tracectx
from analyzer_tpu.obs.tracer import bind_trace
from analyzer_tpu.lint.ownership import thread_role
from analyzer_tpu.sched import pack_schedule, rate_history
from analyzer_tpu.service.broker import Broker, Message
from analyzer_tpu.service.encode import EncodedBatch

logger = get_logger(__name__)


def _mirrored_counter(attr: str, series: str):
    """A per-worker integer attribute whose positive deltas mirror into
    the process-wide registry counter ``series`` — so ``w.matches_rated
    += n`` (the call sites, including the pipeline engine's harvest)
    keeps working while every increment also lands on the metrics
    surface. The attribute stays per-worker (two competing consumers
    report their own numbers); the registry series is process-wide, like
    any Prometheus counter."""

    def fget(self):
        return getattr(self, "_" + attr, 0)

    def fset(self, value):
        delta = value - getattr(self, "_" + attr, 0)
        if delta > 0:
            get_registry().counter(series).add(delta)
        setattr(self, "_" + attr, value)

    return property(fget, fset)

# The service scan's step dimension is FIXED: schedules pad to a multiple
# of this and the scan runs in chunks of exactly this many supersteps.
# With the step shape constant, the compile ladder collapses from
# (row-bucket x step-bucket) — 64 combos a warmup could never cover — to
# the row-bucket ladder alone (8 shapes, all warmed). An adversarially
# chained 500-message batch (steps ~ 500) just runs more chunks of the
# one compiled shape instead of compiling a 512-step scan on first sight.
SERVICE_STEP_CHUNK = 8


class Worker:
    # Operator counters: per-worker values whose increments mirror into
    # the process-wide registry (docs/observability.md catalog).
    matches_rated = _mirrored_counter(
        "matches_rated", "worker.matches_rated_total"
    )
    batches_failed = _mirrored_counter(
        "batches_failed", "worker.batches_failed_total"
    )
    batches_ok = _mirrored_counter("batches_ok", "worker.batches_ok_total")
    dead_letters = _mirrored_counter(
        "dead_letters", "worker.dead_letters_total"
    )
    pipeline_engine_failures = _mirrored_counter(
        "pipeline_engine_failures", "worker.pipeline_engine_failures_total"
    )

    def __init__(
        self,
        broker: Broker,
        store,
        config: ServiceConfig | None = None,
        rating_config: RatingConfig | None = None,
        clock=time.monotonic,
        pipeline: bool | None = None,
        obs_port: int | None = None,
        obs_host: str | None = None,
        flight_dir: str | None = None,
        serve_port: int | None = None,
        serve_host: str | None = None,
        serve_shards: int | None = None,
        profile_dir: str | None = None,
        slo_plane: bool = True,
        audit: bool | None = None,
        audit_sample_denom: int | None = None,
        audit_seed: int = 0,
        quality: bool = True,
        history_interval_s: float = 1.0,
    ) -> None:
        self.broker = broker
        self.store = store
        self.config = config or ServiceConfig.from_env()
        self.rating_config = rating_config or RatingConfig.from_env()
        self.clock = clock
        self.queue: list[Message] = []
        self._first_message_at: float | None = None
        self._queue_depth_sampled_at: float | None = None
        self.matches_rated = 0
        self.batches_failed = 0
        self.batches_ok = 0
        self.dead_letters = 0
        self._started_at = clock()
        self._stop_requested = False
        # Pipelined consume loop (service/pipeline.py): overlap the next
        # batch's load/encode with the in-flight batch's device round
        # trip + commit. None = follow config.pipeline.
        self.pipeline_enabled = (
            self.config.pipeline if pipeline is None else pipeline
        )
        self._engine = None
        self._pipeline_requested = self.pipeline_enabled
        # Transient engine-construction failures retry with backoff
        # instead of permanently degrading the worker (ADVICE r4): a
        # brief DB blip at the clone probe must not halve throughput
        # until restart.
        self._engine_retry_at: float | None = None
        self._engine_backoff = 5.0
        self.pipeline_engine_failures = 0
        # Filled by warmup's probe when lag is auto (config.pipeline_lag
        # None); PipelineEngine reads them through choose_pipeline_lag.
        self.measured_rtt_s: float | None = None
        self.measured_host_s: float | None = None
        # Pinned schedule width: auto-sizing per AMQP batch would give
        # every distinct (steps, width) shape a fresh XLA compile — a
        # latency spike the reference never had (its BATCHSIZE is fixed,
        # worker.py:18). One width derived once from the batch size (a
        # 500-match batch of mostly-distinct players packs into ~8 steps
        # of 64), with step counts bucketed to powers of two in process().
        w = -(-self.config.batch_size // 8)  # ~steps-of-8 heuristic width
        self._packed_width = min(128, max(8, -(-w // 8) * 8))
        # The SINGLE owners of the service compile-shape knobs — schedule
        # bucketing, warmup, and the pipelined engine all read these, so
        # overriding one on a worker keeps every consumer in lockstep.
        self._step_chunk = SERVICE_STEP_CHUNK
        from analyzer_tpu.core.state import MAX_TEAM_SIZE
        from analyzer_tpu.service.encode import row_bucket

        self._canon_rows = (
            row_bucket(self.config.batch_size * 2 * MAX_TEAM_SIZE) + 1
        )

        # Service-lane journal mode: WAL overlap + cheap commits (see
        # SqlStore.enable_wal — deliberately NOT on for the bulk
        # full-history lane, where WAL measured 1.7x slower).
        enable_wal = getattr(store, "enable_wal", None)
        if enable_wal is not None:
            enable_wal()

        c = self.config
        # The reference declares queue/failed/crunch/telesuck but NOT sew
        # (worker.py:87-90) — sew is assumed to exist; we keep that contract.
        broker.declare_queue(c.queue)
        broker.declare_queue(c.failed_queue)
        broker.declare_queue(c.crunch_queue)
        broker.declare_queue(c.telesuck_queue)

        # Flight recorder: the ring is always on (process-wide, shared
        # with the pipeline writer's breadcrumbs); artifact dumps engage
        # once a directory is configured (flight_dir here, or
        # ANALYZER_TPU_FLIGHT_DIR in the environment).
        self.flight = get_flight_recorder()
        if flight_dir is not None:
            self.flight.configure(base_dir=flight_dir)
        # Device-time attribution (obs/prof.py): armed by profile_dir
        # here or ANALYZER_TPU_PROFILE_DIR; unarmed it costs one
        # attribute read per batch. SIGUSR2 requests a capture of the
        # next dispatch window; dead-letters/degradation request one
        # automatically (throttled) so the flight dump gets device
        # timing next to the host-side trace.
        self.profiler = get_device_profiler()
        if profile_dir is not None:
            self.profiler.configure(profile_dir=profile_dir)
        # obsd (obs/server.py): the live introspection plane. Readiness
        # combines the pipeline lane's health with duck-typed broker/
        # store connectivity probes — `curl :port/readyz` flips to 503
        # the moment the worker degrades to the sequential loop.
        self.obs_server = None
        if obs_port is not None:
            from analyzer_tpu.obs.server import (
                DEFAULT_HOST, ObsServer, connectivity_probe,
            )

            self.obs_server = ObsServer(
                port=obs_port,
                host=obs_host or DEFAULT_HOST,
                status_provider=self.stats,
                # /debug/flight rides the worker's own dump path so a
                # remotely-triggered artifact (a fleet Collector at
                # burn onset, obs/federate.py) carries the config +
                # device-profiler blocks a local trigger would.
                flight_dump=self._flight_dump,
            )
            health = self.obs_server.health
            health.register("worker.pipeline", self._pipeline_health)
            health.register(
                "service.broker", connectivity_probe(broker, "broker")
            )
            health.register(
                "service.store", connectivity_probe(store, "store")
            )
        # ratesrv (serve/): the query-serving read plane. The worker
        # publishes a new immutable view version at every batch commit
        # boundary (_publish_view — sequential process() and the
        # pipelined harvest both route through it), so readers see
        # exactly the committed table, never a mid-commit one.
        self.view_publisher = None
        self.query_engine = None
        self.serve_server = None
        if serve_port is not None:
            from analyzer_tpu.obs.httpd import DEFAULT_HOST as LOOPBACK
            from analyzer_tpu.serve import (
                QueryEngine,
                ShardedQueryEngine,
                ShardedViewPublisher,
                ViewPublisher,
            )
            from analyzer_tpu.serve.server import ServeServer

            # Topology is a constructor knob, not a caller concern: both
            # planes satisfy the ServePlane protocol, so everything from
            # _publish_view to /v1/* is identical either way — and the
            # served numbers are bit-identical by the sharded engine's
            # contract (tests/test_serve_sharded.py).
            if serve_shards is not None and serve_shards > 1:
                self.view_publisher = ShardedViewPublisher(serve_shards)
                self.query_engine = ShardedQueryEngine(
                    self.view_publisher, cfg=self.rating_config
                ).start()
            else:
                self.view_publisher = ViewPublisher()
                self.query_engine = QueryEngine(
                    self.view_publisher, cfg=self.rating_config
                ).start()
            self.serve_server = ServeServer(
                self.query_engine,
                port=serve_port,
                host=serve_host or LOOPBACK,
            )
            if self.obs_server is not None:
                # /readyz flips green only after the first commit
                # publishes version 1 — a balancer must not route reads
                # at a worker still warming its view.
                self.obs_server.health.register(
                    "serve.view", self._serve_view_health
                )
        # The live SLO plane (docs/observability.md "History rings /
        # SLO engine / Shadow audit"): the history sampler records the
        # registry into trend rings on THIS worker's clock (vclock-
        # deterministic under the soak), the watchdog evaluates the
        # declarative objective table as multi-window burn rates over
        # those rings — flipping /readyz degraded and capturing a
        # flight dump + device profile at first burn — and the shadow
        # auditor replays a seeded-hash sample of served queries
        # through the bit-exact oracle off the hot path. One throttled
        # _slo_tick per poll; slo_plane=False disables all three (the
        # bit-identity AB knob).
        self.history = None
        self.watchdog = None
        self.auditor = None
        self._history_interval_s = float(history_interval_s)
        self._history_sampled_at: float | None = None
        if slo_plane:
            from analyzer_tpu.obs.history import get_history
            from analyzer_tpu.obs.slo import get_watchdog

            self.history = get_history()
            from analyzer_tpu.obs.devicemem import maybe_sample

            # HBM + cold-tier gauges refresh ahead of every sample so
            # memory growth is trend-visible (the leak burn-rate SLO's
            # data source).
            self.history.add_probe(maybe_sample)
            self.watchdog = get_watchdog()
            self.watchdog.on_burn = self._on_slo_burn
            if self.obs_server is not None:
                self.obs_server.health.register(
                    "slo.watchdog", self.watchdog.healthy
                )
            if audit is None:
                audit = bool(
                    os.environ.get("ANALYZER_TPU_AUDIT", "") not in ("", "0")
                )
            if audit and self.query_engine is not None:
                from analyzer_tpu.obs.audit import (
                    DEFAULT_SAMPLE_DENOM,
                    ShadowAuditor,
                )

                self.auditor = ShadowAuditor(
                    cfg=self.rating_config,
                    tier_edges=self.query_engine.tier_edges,
                    seed=audit_seed,
                    sample_denom=(
                        audit_sample_denom if audit_sample_denom is not None
                        else DEFAULT_SAMPLE_DENOM
                    ),
                )
                self.query_engine.auditor = self.auditor
        # The rating-quality plane (obs/quality.py, docs/observability.md
        # "Rating quality"): at every sequential commit the ledger
        # scores the batch's PRE-update predicted win probabilities
        # (the serve plane's exact Phi link over the prior ratings)
        # against the realized outcomes, mirrored into quality.*
        # counters; drift snapshots ride the throttled _slo_tick. An
        # observer by construction — nothing here feeds back into the
        # rating path, so the soak's deterministic block is
        # bit-identical with the plane on or off (quality=False is the
        # AB knob, like slo_plane).
        self.quality = None
        if quality:
            from analyzer_tpu.obs.quality import (
                CalibrationLedger,
                set_quality_ledger,
            )

            self.quality = CalibrationLedger(self.rating_config)
            set_quality_ledger(self.quality)
        # Fabric membership (analyzer_tpu/fabric): set by the fabric
        # host wiring to a zero-arg callable returning the directory's
        # ``stats()['fabric']`` block — host index, owned shards, the
        # fleet version vector — so /statusz shows the topology without
        # the worker importing the fabric package. None on every
        # non-fabric worker; scrapers key on presence.
        self.fabric_info = None

    # -- micro-batcher ----------------------------------------------------
    def poll(self) -> bool:
        """One consumer iteration: pull what's available, flush when the
        batch is full or the idle timer expired. Returns True if a flush
        happened."""
        room = self.config.batch_size - len(self.queue)
        if room > 0:
            got = self.broker.get(self.config.queue, room)
            if got and self._first_message_at is None:
                self._first_message_at = self.clock()
            self.queue.extend(got)
        self._sample_queue_depth()
        self._slo_tick()
        full = len(self.queue) >= self.config.batch_size
        idle = (
            self._first_message_at is not None
            and self.clock() - self._first_message_at >= self.config.idle_timeout
        )
        if self.queue and (full or idle):
            self.try_process()
            return True
        if self._engine is not None:
            # No new flush: apply whatever batches completed (acks must
            # not wait for the next flush), but do NOT block on the
            # in-flight tail — a push broker legitimately returns empty
            # polls while deliveries are in flight (broker.py:95+), and
            # draining there would serialize the pipeline back to the
            # sequential loop. Full drains happen on stop, bounded-run
            # exit, and explicit Worker.drain().
            self._engine.harvest()
        return False

    def _sample_queue_depth(self) -> None:
        """Samples the broker's ready depth into the
        ``broker.queue_depth{queue=}`` gauge (plus the unlabeled
        process gauge) so soak/production backpressure is visible on
        /statusz. On a partitioned broker the ``{queue=}`` series is
        the AGGREGATE across every partition and lane (``qsize`` owns
        that sum — a per-partition broker whose gauge reported one
        partition's depth would hide a hot-partition backlog behind a
        small number), and each partition/lane additionally emits its
        own ``broker.queue_depth{queue=,partition=,lane=}`` series so
        /statusz shows the SKEW, bounded by the registry's
        label-cardinality cap. Throttled on the worker clock — on AMQP
        the depth is a passive-declare round trip, which a 100 Hz poll
        loop must not pay per iteration. Best-effort: a broker blip
        here must not take down the consume loop."""
        qsize = getattr(self.broker, "qsize", None)
        if qsize is None:
            return
        now = self.clock()
        if (
            self._queue_depth_sampled_at is not None
            and now - self._queue_depth_sampled_at < 1.0
        ):
            return
        self._queue_depth_sampled_at = now
        try:
            depth = int(qsize(self.config.queue))
        except Exception:  # noqa: BLE001 — observability is best-effort
            logger.debug("broker qsize probe failed", exc_info=True)
            return
        reg = get_registry()
        reg.gauge("broker.queue_depth").set(depth)
        reg.gauge("broker.queue_depth", queue=self.config.queue).set(depth)
        partition_depths = getattr(self.broker, "partition_depths", None)
        if partition_depths is None:
            return
        try:
            per_part = partition_depths(self.config.queue)
        except Exception:  # noqa: BLE001 — observability is best-effort
            logger.debug("broker partition_depths probe failed", exc_info=True)
            return
        for part, lanes in per_part.items():
            for lane, lane_depth in lanes.items():
                reg.gauge(
                    "broker.queue_depth",
                    queue=self.config.queue, partition=part, lane=lane,
                ).set(lane_depth)

    def _slo_tick(self) -> None:
        """One throttled pass of the live SLO plane: refresh the serve
        gauges the sampler reads, record a history sample at THIS
        worker's clock, drain a bounded slice of the shadow-audit
        backlog (the oracle replay runs here — the consumer loop's
        idle shoulder — never on the serving path), and evaluate the
        watchdog. Behavior-neutral by construction: nothing here
        branches into the rating path, so the soak's deterministic
        block is bit-identical with the plane on or off (pinned)."""
        if self.history is None:
            return
        now = self.clock()
        if (
            self._history_sampled_at is not None
            and now - self._history_sampled_at < self._history_interval_s
        ):
            return
        self._history_sampled_at = now
        try:
            if self.view_publisher is not None:
                reg = get_registry()
                reg.gauge("serve.view_version").set(self.view_publisher.version)
                age = self.view_publisher.view_age_s()
                if age is not None:
                    reg.gauge("serve.view_age_seconds").set(round(age, 3))
            if self.auditor is not None:
                self.auditor.drain(limit=64)
            if self.quality is not None and self.view_publisher is not None:
                # Population-drift snapshot over the COMMITTED table
                # (the served view — the same surface readers see):
                # PSI vs the pinned reference window + sigma
                # convergence by games-played cohort, throttled to the
                # history interval like everything else on this tick.
                view = self.view_publisher.current()
                if view is not None:
                    self.quality.observe_population(
                        view.host_table(), now=now
                    )
            self.history.sample(now)
            if self.watchdog is not None:
                self.watchdog.check(now)
        except Exception:  # noqa: BLE001 — the SLO plane must never
            # take down the consume loop it observes.
            logger.exception("SLO plane tick failed")

    def _on_slo_burn(self, objective, burn) -> None:
        """First-burn evidence capture: the flight recorder freezes the
        trajectory INTO the burn (history.json rides the dump) and the
        device profiler arms a capture of the next dispatch window —
        both throttled, both no-ops when unarmed."""
        logger.warning(
            "SLO burn: %s — %s", objective.name, burn.detail
        )
        if (
            getattr(objective, "kind", None) == "calibration"
            and self.quality is not None
        ):
            # Name the worst reliability bin while the evidence is
            # fresh — the triage runbook's first question is WHERE the
            # predictions are off, not just that they are.
            wb = self.quality.worst_bin()
            if wb is not None:
                logger.warning(
                    "calibration burn: worst reliability bin "
                    "[%s, %s): mean_p=%s mean_y=%s over %s matches",
                    wb["lo"], wb["hi"], wb["mean_p"], wb["mean_y"],
                    wb["count"],
                )
                self.flight.note("quality.worst_bin", **wb)
        self.flight.note(
            "slo.burn", objective=objective.name, detail=burn.detail
        )
        self.profiler.request("slo_burn")
        self._flight_dump(f"slo-{objective.name}")

    @thread_role("any")
    def request_stop(self) -> None:
        """Asks the consume loop to exit after the current batch. Safe
        from a signal handler (single flag write). The reference has no
        graceful shutdown at all (``worker.py:219-221`` — SIGTERM kills
        mid-batch and relies on broker redelivery); here an in-flight
        batch always finishes its commit + acks first."""
        self._stop_requested = True

    @thread_role("consumer")
    def run(
        self,
        max_flushes: int | None = None,
        poll_interval: float = 0.01,
        max_wall_s: float | None = None,
        install_signal_handlers: bool = False,
    ) -> None:
        """Blocking consume loop (the reference's ``start_consuming``).
        ``max_wall_s`` bounds a ``max_flushes`` run in wall-clock time so
        a test against a mis-seeded broker fails loudly instead of
        spinning forever. ``install_signal_handlers`` wires SIGTERM and
        SIGINT to :meth:`request_stop` (drain in-flight batches, flush a
        final snapshot, exit cleanly) and SIGUSR1 to a flight-recorder
        dump + ``stats()`` log line WITHOUT stopping — the operator's
        "what is this worker doing right now" signal (main-thread only)."""
        # NOT reset here: a stop requested before run() must be honored
        # (it is cleared on the stop exit below so the worker is reusable).
        previous_handlers = {}
        if install_signal_handlers:
            import signal

            for sig in (signal.SIGTERM, signal.SIGINT):
                previous_handlers[sig] = signal.signal(
                    sig, lambda *_: self.request_stop()
                )
            if hasattr(signal, "SIGUSR1"):  # not on Windows
                previous_handlers[signal.SIGUSR1] = signal.signal(
                    signal.SIGUSR1, self._on_sigusr1
                )
            if hasattr(signal, "SIGUSR2"):  # on-demand device capture
                previous_handlers[signal.SIGUSR2] = signal.signal(
                    signal.SIGUSR2, self._on_sigusr2
                )
        try:
            flushes = 0
            deadline = None if max_wall_s is None else self.clock() + max_wall_s
            while max_flushes is None or flushes < max_flushes:
                if self._stop_requested:
                    # In-flight pipelined batches finish their commits +
                    # acks first (the graceful-shutdown contract), THEN
                    # messages pulled into a partial batch go back to the
                    # broker (nack + requeue) — leaving them unacked would
                    # strand them forever on the in-memory broker and
                    # until connection teardown on AMQP.
                    self.drain()
                    for msg in self.queue:
                        self.broker.nack(msg.delivery_tag, requeue=True)
                    self.queue = []
                    self._first_message_at = None
                    self._stop_requested = False
                    logger.info(
                        "stop requested; exiting after %s batches: %s",
                        flushes, self.stats(),
                    )
                    # TERM contract: everything committed + acked above;
                    # flush one last snapshot so the shutdown state is
                    # inspectable after the process is gone.
                    self._final_snapshot()
                    return
                if deadline is not None and self.clock() > deadline:
                    target = "" if max_flushes is None else f"/{max_flushes}"
                    raise TimeoutError(
                        f"worker made {flushes}{target} flushes in "
                        f"{max_wall_s}s"
                    )
                if self.poll():
                    flushes += 1
                else:
                    time.sleep(poll_interval)
            self.drain()  # bounded runs return with everything committed
        finally:
            if previous_handlers:
                import signal

                for sig, handler in previous_handlers.items():
                    signal.signal(sig, handler)

    # -- warmup -----------------------------------------------------------
    def warmup(self) -> None:
        """Pre-compiles the rating scan for EVERY shape production
        batches can hit, so no message ever pays XLA compilation (the
        reference's pure-Python loop had no compile step to hide; here
        it's real first-request latency).

        The shape space is small by construction: the schedule width is
        pinned, the scan's step dimension is fixed at
        ``SERVICE_STEP_CHUNK`` (any chain depth = more chunks of the one
        shape), and the team axis is always ``MAX_TEAM_SIZE`` — so the
        only free dimension is the player-row bucket, a power-of-two
        ladder from 64 up to ``row_bucket(batch_size * 2 * 5)`` (8
        values at the reference's BATCHSIZE=500). The whole ladder is
        compiled here, including the pipelined engine's chaining scatter
        on each ladder rung's square pair (consecutive batches share a
        bucket in steady state; a mixed-size pair — a full batch right
        after an idle flush — is a rare, sub-second one-off compile).
        ``tests/test_service.py::TestCompileChurn`` asserts an
        adversarially chained batch after warmup compiles NOTHING."""
        import numpy as np

        from analyzer_tpu.core.state import MAX_TEAM_SIZE, PlayerState
        from analyzer_tpu.sched.superstep import MatchStream

        t0 = self.clock()
        max_alloc = self._canon_rows - 1  # one owner: the constructor
        ladder = []
        alloc = 64  # row_bucket's floor
        while alloc <= max_alloc:
            ladder.append(alloc)
            alloc *= 2
        for alloc in ladder:
            # A matches-worth of distinct players filling this bucket
            # (any occupancy compiles the same (rows, chunk) shape).
            p = min(alloc, self.config.batch_size * 2 * MAX_TEAM_SIZE)
            n_matches = max(1, p // (2 * MAX_TEAM_SIZE))
            p = n_matches * 2 * MAX_TEAM_SIZE
            state = PlayerState.create(alloc, cfg=self.rating_config)
            idx = np.arange(p, dtype=np.int32).reshape(
                n_matches, 2, MAX_TEAM_SIZE
            )
            stream = MatchStream(
                player_idx=idx,
                winner=np.zeros(n_matches, np.int32),
                mode_id=np.ones(n_matches, np.int32),  # ranked
                afk=np.zeros(n_matches, bool),
            )
            sched = self._bucketed_schedule(stream, alloc)
            rate_history(
                state, sched, self.rating_config, collect=True,
                steps_per_chunk=self._step_chunk,
            )
        if self.pipeline_enabled and self.config.pipeline_lag is None:
            try:
                self._measure_pipeline_costs()
            except Exception:  # noqa: BLE001 — optimization-only probe:
                # a transient device error here must not kill startup;
                # the engine falls back to DEFAULT_LAG.
                logger.exception(
                    "pipeline cost probe failed; lag falls back to the "
                    "default"
                )
        if self.pipeline_enabled:
            import jax.numpy as jnp

            from analyzer_tpu.core.state import TABLE_WIDTH
            from analyzer_tpu.service.pipeline import (
                _canonical_rows, _chain_patch_pairs, _ring_put,
                chain_buffers,
            )

            # The probe ran FIRST so the ring compiles at the lag the
            # engine will actually resolve; one owner (chain_buffers)
            # keeps these the shapes production hits.
            lag = self.resolved_pipeline_lag()
            canon = self._canon_rows
            ring, pairs, _ = chain_buffers(lag, canon)
            src = jnp.zeros((canon, TABLE_WIDTH), jnp.float32)
            ring = _ring_put(ring, 0, src)  # donates its input: reassign
            ring.block_until_ready()
            for alloc in ladder:
                # Every batch's final table canonicalizes once (per-rung
                # compile) and every destination rung patches the whole
                # ring in one call — 2 compiles per rung, not rung^2.
                _canonical_rows(
                    jnp.zeros((alloc + 1, TABLE_WIDTH), jnp.float32), canon
                ).block_until_ready()
                dst = jnp.zeros((alloc + 1, TABLE_WIDTH), jnp.float32)
                _chain_patch_pairs(dst, ring, pairs).block_until_ready()
        logger.info(
            "warmup compiled the %d-rung row ladder in %.1fs",
            len(ladder), self.clock() - t0,
        )

    def _measure_pipeline_costs(self) -> None:
        """Feeds ``choose_pipeline_lag``: the dispatch->fetch round trip
        of one production-sized packed-outputs chunk (the latency the
        pipeline must hide; min of 3 after a compile rep) and the
        per-batch host cost of encode + schedule + write_back on a
        synthetic batch-size object graph (the work that hides it).
        Store load/commit costs add to the host side in production,
        which only LOWERS the ideal lag — an over-estimate costs broker
        headroom and failure blast radius, not throughput, so the probe
        deliberately errs high."""
        import jax.numpy as jnp
        import numpy as np

        from analyzer_tpu.core.state import MAX_TEAM_SIZE

        # One scan chunk's collect output: [chunk, width, 3 + 10T] f32 —
        # a full 500-match batch of mostly-distinct players packs into
        # about one such chunk (~108 KB at the defaults).
        shape = (self._step_chunk, self._packed_width, 3 + 10 * MAX_TEAM_SIZE)
        base = jnp.zeros(shape, jnp.float32)
        base.block_until_ready()
        rtt: float | None = None
        for i in range(4):
            t0 = self.clock()
            np.asarray(base + jnp.float32(i))  # fresh array: no host cache
            dt = self.clock() - t0
            if i > 0:  # rep 0 pays the add's compile
                rtt = dt if rtt is None else min(rtt, dt)
        # The probe must measure the LANE production batches will run —
        # the columnar encode is several times cheaper than the object
        # one, and an inflated host estimate would under-size the lag
        # (lag ~ rtt / host).
        columnar = getattr(self.store, "load_batch_raw", None) is not None
        if columnar:
            from analyzer_tpu.fixtures import synthetic_raw_batch
            from analyzer_tpu.service.columnar import ColumnarBatch

            t0 = self.clock()
            enc = ColumnarBatch(
                synthetic_raw_batch(self.config.batch_size),
                self.rating_config, bucket_rows=True,
            )
        else:
            from analyzer_tpu.fixtures import synthetic_batch

            matches = synthetic_batch(self.config.batch_size)
            t0 = self.clock()
            enc = EncodedBatch(matches, self.rating_config, bucket_rows=True)
        sched = self._bucketed_schedule(enc.stream, enc.state.pad_row)
        host = self.clock() - t0
        _, outs = rate_history(
            enc.state, sched, self.rating_config, collect=True,
            steps_per_chunk=self._step_chunk,
        )
        t0 = self.clock()
        if columnar:
            enc.write_plan(outs)
        else:
            enc.write_back(outs)
        host += self.clock() - t0
        self.measured_rtt_s = rtt
        self.measured_host_s = host
        logger.info(
            "pipeline cost probe: rtt %.0f ms, host %.0f ms/batch",
            (rtt or 0.0) * 1e3, host * 1e3,
        )

    def resolved_pipeline_lag(self) -> int:
        """The commit lag the pipelined engine will run with: the pinned
        ``PIPELINE_LAG`` when set, else the warmup probe's measurement
        through ``choose_pipeline_lag``, else the default. One owner —
        warmup compiles the chain ring at this depth and the engine must
        build it identically."""
        from analyzer_tpu.service.pipeline import (
            DEFAULT_LAG, choose_pipeline_lag,
        )

        if self.config.pipeline_lag is not None:
            return max(1, int(self.config.pipeline_lag))
        if self.measured_rtt_s is not None and self.measured_host_s is not None:
            lag = choose_pipeline_lag(self.measured_rtt_s, self.measured_host_s)
            logger.info(
                "pipeline lag auto-tuned to %d (rtt %.0f ms, host "
                "%.0f ms/batch)", lag, self.measured_rtt_s * 1e3,
                self.measured_host_s * 1e3,
            )
            return lag
        return DEFAULT_LAG

    # -- batch pipeline ---------------------------------------------------
    def _bucketed_schedule(self, stream, pad_row: int):
        """Pinned width + fixed step-chunk multiple — the ONE place the
        service schedule shapes are derived, shared by ``process``,
        ``warmup`` and the pipelined engine so the warmed shapes are
        exactly production's. The scan consumes the schedule in chunks of
        ``SERVICE_STEP_CHUNK`` steps, so ANY chain depth reuses the one
        compiled (rows, chunk) shape."""
        sched = pack_schedule(
            stream, pad_row=pad_row, batch_size=self._packed_width
        )
        c = self._step_chunk
        return sched.pad_to_steps(-(-sched.n_steps // c) * c)

    def _dead_letter(self, messages) -> None:
        """Republish to the failed queue + nack without requeue — the
        reference's failure policy (``worker.py:110-120``), applied here
        to whatever subset the caller determined."""
        rollback = getattr(self.store, "rollback", None)
        if rollback is not None:
            # Close out any read transaction load_batch's SELECTs opened
            # (the reference's rollback-then-close, worker.py:195-199);
            # without this a MySQL connection would pin a stale snapshot
            # and the next load_batch would miss newly ingested matches.
            rollback()
        for msg in messages:
            self.broker.publish(self.config.failed_queue, msg.body, msg.headers)
            self.broker.nack(msg.delivery_tag, requeue=False)
        self.dead_letters += len(messages)
        get_tracer().instant(
            "worker.dead_letter", cat="worker", messages=len(messages)
        )
        # The flight recorder freezes the last seconds BEFORE this point
        # — spans, log tail, batch breadcrumbs — into an artifact dir
        # (throttled; obs/flight.py). The failure policy above already
        # completed, so a dump failure costs nothing but the artifact.
        self.flight.note("dead_letter", messages=len(messages))
        # Device-time attribution for the failure window: ask for a
        # (throttled) jax.profiler capture of the NEXT dispatch so the
        # dump below names device timing next to the host-side trace.
        self.profiler.request("dead_letter")
        self._flight_dump("dead_letter")

    @thread_role("consumer")
    def try_process(self) -> None:
        """Routes the flushed batch: the sequential reference-shaped path
        (default), or the pipelined engine (``service/pipeline.py``) that
        overlaps this batch's device round trip with the next batch's
        host work. Failure policy is identical either way."""
        batch = self.queue
        self.queue = []
        self._first_message_at = None
        mode = "pipelined" if self.pipeline_enabled else "sequential"
        # Causal join (obs/tracectx.py, no-op when tracing is off): one
        # batch.assemble instant maps member match traces -> this batch,
        # and binding the batch id makes every span below — including
        # the feed thread's and the pipelined writer's — part of one
        # reconstructable tree (cli trace).
        trace = tracectx.assemble(batch)
        # The batch lifecycle span: flush -> (encode/rate/commit or
        # dead-letter). In pipelined mode this covers submission only —
        # commit + ack land in a later harvest (their own spans).
        with bind_trace(trace), get_tracer().span(
            "batch.lifecycle", cat="worker", messages=len(batch), mode=mode
        ):
            if self.pipeline_enabled:
                self._try_process_pipelined(batch)
            else:
                self._process_batch_sequential(batch)

    def _ensure_engine(self):
        """Returns the pipelined engine, constructing it on first use, or
        ``None`` when unavailable (caller runs the sequential loop). A
        PERMANENT refusal (RuntimeError from the store's eager clone
        probe — e.g. in-memory sqlite, ``sql_store.py:176``) disables
        pipelined mode for the worker's lifetime; anything else (a
        transient DB outage hitting the probe's connect) keeps pipelined
        mode requested and retries construction after a backoff, so a
        brief blip costs seconds of sequential throughput, not the rest
        of the process. ``pipeline_degraded`` surfaces the state."""
        if self._engine is not None:
            return self._engine
        if not self.pipeline_enabled:
            return None
        now = self.clock()
        if self._engine_retry_at is not None and now < self._engine_retry_at:
            return None
        from analyzer_tpu.service.pipeline import PipelineEngine
        from analyzer_tpu.service.store import UncloneableStoreError

        try:
            self._engine = PipelineEngine(self, lag=self.config.pipeline_lag)
        except UncloneableStoreError as err:
            self._disable_pipeline(f"store refuses a second connection: {err}")
            return None
        except Exception as err:  # noqa: BLE001 — transient: retry later
            self.pipeline_engine_failures += 1
            self._engine_retry_at = now + self._engine_backoff
            logger.warning(
                "pipeline engine construction failed (%s); sequential "
                "loop for ~%.0f s, then retrying", err, self._engine_backoff,
            )
            self._engine_backoff = min(self._engine_backoff * 2, 300.0)
            return None
        self._engine_retry_at = None
        self._engine_backoff = 5.0
        return self._engine

    def _disable_pipeline(self, reason: str) -> None:
        """Permanently degrades the worker to the sequential loop (store
        can never clone; writer died). Narrows the broker's QoS window
        back to the reference's one-batch bound when the broker supports
        it — the pipelined prefetch (lag+1 batches) would otherwise keep
        hogging deliveries a sequential consumer can't keep up with,
        starving healthy competing consumers on the same queue."""
        self.pipeline_enabled = False
        self._engine = None
        get_registry().counter("worker.pipeline_degradations_total").add(1)
        get_registry().gauge("worker.pipeline_degraded").set(True)
        get_tracer().instant(
            "worker.pipeline_degraded", cat="worker", reason=reason
        )
        logger.warning(
            "pipelined mode disabled (%s); using the sequential loop",
            reason,
        )
        self.profiler.request("pipeline_degraded")
        self._flight_dump("pipeline_degraded")
        set_prefetch = getattr(self.broker, "set_prefetch", None)
        if set_prefetch is not None:
            try:
                set_prefetch(self.config.batch_size)
            except Exception:  # noqa: BLE001 — QoS narrowing is best-effort
                logger.exception("could not narrow broker prefetch")

    def drain(self) -> None:
        """Blocks until every in-flight pipelined batch has committed (or
        its failure policy has been applied). No-op in sequential mode.
        Also drains the shadow-audit backlog: a bounded-run exit must
        not leave sampled queries unreplayed (the soak's
        ``audit.mismatches_total == 0`` acceptance reads after this)."""
        if self._engine is not None:
            self._engine.drain()
        if self.auditor is not None:
            self.auditor.drain()

    def close(self) -> None:
        """Releases the pipelined engine (writer thread + its cloned
        store connection) after draining, and stops obsd + ratesrv. A
        Worker is reusable after close — the next pipelined flush builds
        a fresh engine (obsd/ratesrv are not rebuilt: their lifetime is
        the process's)."""
        if self._engine is not None:
            self._engine.close()
            self._engine = None
        if self.auditor is not None:
            self.auditor.drain()
        if self.watchdog is not None and self.watchdog.on_burn == self._on_slo_burn:
            # The watchdog is process-wide; a closed worker must not
            # keep receiving burn callbacks through it.
            self.watchdog.on_burn = None
        if self.quality is not None:
            from analyzer_tpu.obs.quality import (
                get_quality_ledger,
                set_quality_ledger,
            )

            # The /qualityz registration is process-wide; release it
            # only if it is still ours (a newer worker may own it).
            if get_quality_ledger() is self.quality:
                set_quality_ledger(None)
        if self.serve_server is not None:
            self.serve_server.close()
            self.serve_server = None
        if self.query_engine is not None:
            self.query_engine.close()
            self.query_engine = None
        if self.obs_server is not None:
            self.obs_server.close()
            self.obs_server = None

    @thread_role("consumer")
    def _try_process_pipelined(self, batch) -> None:
        from analyzer_tpu.service.pipeline import PipelineFallback

        engine = self._ensure_engine()
        if engine is None:  # unavailable (permanent or inside the retry
            # window): the sequential loop owns the batch's failure policy.
            self._process_batch_sequential(batch)
            return
        engine.harvest()  # apply whatever completed since the last flush
        if not self.pipeline_enabled or self._engine is None:
            # harvest itself disabled the pipeline (dead writer):
            # submitting to the orphaned engine would strand this
            # batch's messages unacked in a queue nothing drains.
            self._process_batch_sequential(batch)
            return
        try:
            engine.submit(batch)
        except PipelineFallback:
            # A pending failure poisoned the stream: harvest applies the
            # failure policy + reprocessing, then this batch runs clean.
            engine.harvest()
            self._process_batch_sequential(batch)
        except Exception as err:  # noqa: BLE001 — poison, load errors, ...
            # The sequential path re-loads from scratch and owns the
            # poison-isolation / whole-batch dead-letter decision — but
            # it must see FULLY COMMITTED state and commit in order, so
            # the in-flight pipeline finishes first (the PoisonError
            # retry inside submit drains for the same reason).
            logger.warning(
                "pipelined submit failed (%s); sequential fallback", err
            )
            engine.drain()
            self._process_batch_sequential(batch)

    @thread_role("consumer")
    def _process_batch_sequential(self, batch) -> None:
        """The reference's ``try_process`` (``worker.py:103-166``), with
        POISON-PILL ISOLATION on top: a failure that names its offending
        match(es) (service.encode.PoisonError) dead-letters exactly
        those messages and retries the rest, so one corrupt record costs
        one message instead of the whole 500 (the reference dead-letters
        everything, ``worker.py:110-120``). Unattributable errors keep
        the whole-batch policy."""
        from analyzer_tpu.service.encode import PoisonError

        for _ in range(len(batch) + 1):  # each pass removes >= 1 message
            try:
                self.process([m.body.decode() for m in batch])
                break
            except PoisonError as err:
                bad_ids = set(err.api_ids)
                bad = [m for m in batch if m.body.decode() in bad_ids]
                if not bad:  # can't attribute after all: whole-batch policy
                    logger.error("batch failed: %s", err)
                    self.batches_failed += 1
                    self._dead_letter(batch)
                    return
                logger.error(
                    "poison match(es) %s: %s; dead-lettering %d message(s), "
                    "retrying the other %d",
                    sorted(bad_ids), err, len(bad), len(batch) - len(bad),
                )
                self._dead_letter(bad)
                keep = {id(m) for m in bad}
                batch = [m for m in batch if id(m) not in keep]
                if not batch:
                    return
            except Exception as err:  # noqa: BLE001 — policy: any error dead-letters
                logger.error("batch failed: %s", err)
                self.batches_failed += 1
                self._dead_letter(batch)
                return
        else:  # loop exhausted without success — defensive, unreachable
            self.batches_failed += 1
            self._dead_letter(batch)
            return

        self._ack_batch(batch)

    @thread_role("consumer")
    def _ack_batch(self, batch) -> None:
        """Per-message ack + notify/crunch/sew/telesuck fan-out
        (``worker.py:122-166``). Always on the consumer thread — the
        broker is not thread-safe."""
        logger.info("acking batch")
        get_registry().counter("worker.acks_total").add(len(batch))
        for msg in batch:
            self.broker.ack(msg.delivery_tag)
            notify = (msg.headers or {}).get("notify")
            if notify:
                self.broker.publish_topic("amq.topic", notify, b"analyze_update")
            # Forwards keep the original message headers, as the reference
            # republishes with properties=prop (worker.py:136-147) so
            # downstream consumers still see e.g. the notify header.
            if self.config.do_crunch_match:
                self.broker.publish(self.config.crunch_queue, msg.body, msg.headers)
            if self.config.do_sew_match:
                self.broker.publish(self.config.sew_queue, msg.body, msg.headers)
            if self.config.do_telesuck_match:
                mid = msg.body.decode()
                for url in self.store.asset_urls(mid):
                    self.broker.publish(
                        self.config.telesuck_queue,
                        url.encode(),
                        headers={"match_api_id": mid},
                    )

    def _encode_batch(self, ids: list[str]):
        """Loads + encodes one id batch through the store's best lane:
        columnar (``load_batch_raw`` -> :class:`ColumnarBatch`, no object
        graphs — the SqlStore fast path) or the object lane
        (``load_batch`` -> :class:`EncodedBatch` — required where the
        loaded objects ARE the store, e.g. InMemoryStore). Returns an
        encoded batch whose ``matches`` is empty when no ids loaded."""
        raw_loader = getattr(self.store, "load_batch_raw", None)
        if raw_loader is not None:
            from analyzer_tpu.service.columnar import ColumnarBatch

            raw = None
            native_loader = getattr(self.store, "load_batch_native", None)
            if native_loader is not None:
                # C scanner: typed column arrays, no per-row python
                # tuples (None when unavailable — python rows instead).
                raw = native_loader(ids)
            if raw is None:
                raw = raw_loader(ids)
            return ColumnarBatch(
                raw, self.rating_config, bucket_rows=True
            )
        matches = self.store.load_batch(ids)
        if not matches:
            return None
        return EncodedBatch(matches, self.rating_config, bucket_rows=True)

    def process(self, ids: list[str]) -> list[str]:
        """Rates one batch of match ids. Pure until the final write-back:
        an exception anywhere leaves objects and state untouched."""
        from analyzer_tpu.service.columnar import finalize

        tracer = get_tracer()
        # bucket_rows + pinned width + power-of-two step bucket: the three
        # shapes in the compiled scan's signature (table rows, batch
        # width, step count) all land on a few fixed sizes, so
        # consecutive batches of any size reuse one compiled scan.
        with tracer.span("batch.encode", cat="worker", ids=len(ids)):
            enc = self._encode_batch(ids)
        n = len(enc.matches) if enc is not None else 0
        logger.info("processing batch of %s matches", n)
        self.flight.note_batch(
            len(ids), n, first_id=ids[0] if ids else None
        )
        if not n:
            return []
        # Pre-update prior snapshot for the calibration ledger: ONE
        # compact row gather (never the whole table), taken before
        # rate_history may donate the state buffer; scored after the
        # commit below (obs/quality.py).
        q_prior = (
            self._quality_prior(enc) if self.quality is not None else None
        )
        with tracer.span("batch.pack", cat="worker", matches=n):
            sched = self._bucketed_schedule(enc.stream, enc.state.pad_row)
        with tracer.span(
            "batch.compute", cat="worker", matches=n, steps=sched.n_steps
        ), self.profiler.maybe_capture(
            context={"matches": n, "steps": sched.n_steps}
        ):
            final_state, outs = rate_history(
                enc.state, sched, self.rating_config, collect=True,
                steps_per_chunk=self._step_chunk,
            )
        # Transactional stores (SqlStore) flush in one commit, rolling
        # back internally on error (worker.py:194-199); the in-memory
        # store's objects ARE the store, nothing to flush beyond
        # write_back's mutations.
        with tracer.span("batch.commit", cat="worker", matches=n):
            finalize(self.store, enc, outs)
        # The commit boundary IS the view publish boundary: readers of
        # the serving plane see this batch's posteriors only once the
        # store does (no-op without serve_port).
        self._publish_view(enc, final_state.table)
        if q_prior is not None:
            self._score_quality(q_prior)
        self.matches_rated += n
        self.batches_ok += 1
        logger.info(
            "batch rated: %d matches (%.1f matches/s since start)",
            n, self.matches_per_sec,
        )
        return [
            m if isinstance(m, str) else m.api_id for m in enc.matches
        ]

    def _quality_prior(self, enc) -> tuple | None:
        """The calibration ledger's input: a host snapshot of the
        PRE-update table plus host views of the batch's stream. One
        whole-table device_get per batch — shape-stable, so it never
        touches the compile cache (a compact device GATHER of just the
        batch's rows would retrace on every distinct row count and
        trip the soak's flat-retrace SLO). Never raises — the quality
        plane is an observer and must not take down the consume loop."""
        import numpy as np

        try:
            return (
                np.asarray(enc.state.table),
                np.asarray(enc.stream.player_idx),
                np.asarray(enc.stream.winner),
                np.asarray(enc.stream.mode_id),
                np.asarray(enc.stream.afk),
                int(enc.state.pad_row),
            )
        except Exception:  # noqa: BLE001 — observer plane
            logger.exception("quality prior snapshot failed")
            return None

    def _score_quality(self, prior: tuple) -> None:
        """Scores one committed batch against its pre-update priors."""
        try:
            table, idx, winner, mode_id, afk, pad = prior
            self.quality.score_batch(table, idx, winner, mode_id, afk, pad)
        except Exception:  # noqa: BLE001 — observer plane
            logger.exception("quality scoring failed")

    # -- serving plane ----------------------------------------------------
    @thread_role("consumer")
    def _publish_view(self, enc, table) -> None:
        """Publishes one committed batch's posterior rows into the
        serving plane's view (serve/view.py). ``enc`` supplies the
        api-id -> row map (EncodedBatch and ColumnarBatch both expose
        ``row_of``); ``table`` is the FINAL device table returned by the
        rating scan. No-op when serving is off or the batch carried no
        players (_EmptyBatch). Never raises: a read-plane publish
        failure must not dead-letter a successfully committed batch."""
        if self.view_publisher is None:
            return
        row_of = getattr(enc, "row_of", None)
        if not row_of:
            return
        import numpy as np

        try:
            ids = [None] * len(row_of)
            for pid, row in row_of.items():
                ids[row] = pid
            rows = np.asarray(table)[: len(ids)]
            view = self.view_publisher.publish_rows(ids, rows)
            if tracectx.tracing_enabled():
                # The served-visible anchor of the causal chain: the
                # bound batch trace rides in via args (commit happened
                # strictly before — sequential order, or the pipelined
                # harvest after the writer finished this job).
                get_tracer().instant(
                    "view.publish", cat="trace",
                    version=view.version, players=view.n_players,
                )
            logger.debug(
                "published ratings view v%d (%d players)",
                view.version, view.n_players,
            )
        except Exception:  # noqa: BLE001 — the write plane must not fail
            # because the read plane could not take the update.
            logger.exception("ratings view publish failed")

    def _serve_view_health(self) -> tuple[bool, str]:
        """obsd readiness probe: green once a view has been published."""
        view = self.view_publisher.current()
        if view is None:
            return False, "no ratings view published yet"
        return True, f"view v{view.version} ({view.n_players} players)"

    # -- observability ----------------------------------------------------
    def _pipeline_health(self) -> tuple[bool, str]:
        """Readiness probe: a degraded pipelined worker still serves (the
        sequential loop rates correctly) but at roughly half throughput —
        a load balancer should stop preferring it, which is exactly what
        a 503 readiness means."""
        if self.pipeline_degraded:
            return False, "pipeline degraded: sequential fallback active"
        if self.pipeline_enabled:
            return True, "pipelined"
        return True, "sequential by config"

    def _flight_dump(self, reason: str, force: bool = False) -> str | None:
        """One flight-recorder artifact for a failure path. Never raises
        (obs/flight.py owns the throttle + error swallowing); the config
        capture rides along so the artifact explains the worker's knobs,
        and the device profiler's capture info names the jax.profiler
        artifact directory when one is armed. Returns the artifact path
        (None when unarmed or throttled) — obsd's /debug/flight trigger
        reports it to the requesting Collector."""
        return self.flight.dump(
            reason, config=dataclasses.asdict(self.config), force=force,
            profile=self.profiler.capture_info(),
        )

    def _on_sigusr1(self, *_args) -> None:
        """SIGUSR1: dump + stats WITHOUT stopping. Runs on the main
        thread between bytecodes (Python signal semantics), so the file
        IO here cannot interleave with a batch mid-commit."""
        logger.info("SIGUSR1: %s", self.stats())
        self._flight_dump("sigusr1", force=True)

    def _on_sigusr2(self, *_args) -> None:
        """SIGUSR2: request a jax.profiler capture of the NEXT batch's
        dispatch window (no-op + a log line when no --profile-dir is
        armed). Force-bypasses the throttle — an operator asking twice
        means it."""
        if not self.profiler.armed:
            logger.info(
                "SIGUSR2: no profile dir armed (--profile-dir / "
                "ANALYZER_TPU_PROFILE_DIR); ignoring capture request"
            )
            return
        self.profiler.request("sigusr2", force=True)

    def _final_snapshot(self) -> None:
        """The graceful-shutdown snapshot: written into the flight
        recorder's directory (no-op when none is configured — tests and
        embedded workers must not litter their cwd)."""
        base = self.flight.base_dir
        if base is None:
            return
        from analyzer_tpu.obs import write_snapshot

        try:
            os.makedirs(base, exist_ok=True)
            path = os.path.join(base, f"final-snapshot-{os.getpid()}.json")
            write_snapshot(path)
            logger.info("final metrics snapshot written to %s", path)
        except Exception:  # noqa: BLE001 — shutdown must complete regardless
            logger.exception("final snapshot failed")

    @property
    def matches_per_sec(self) -> float:
        dt = self.clock() - self._started_at
        return self.matches_rated / dt if dt > 0 else 0.0

    @thread_role("any")
    def stats(self) -> dict:
        """One operator-facing snapshot of the counters the reference
        never had (SURVEY.md section 5.5: its only observability was
        debug logs): throughput, failure counts, and the pipelined
        lane's health — ready for a metrics scraper or a periodic log
        line. Since the obs subsystem landed this is a VIEW over the
        registry-mirrored counters (the counting sites moved there); it
        also pushes the worker's current gauges, so a snapshot taken
        right after ``stats()`` carries the same picture.
        ``tests/test_service.py::TestStats`` pins the key schema — a
        dropped key here silently breaks a metrics scraper."""
        # The engine is built lazily at the first flush, but the lag is
        # already resolved (warmup probe / pinned config) — report it
        # whenever pipelined mode is on, None only when it's off.
        lag = (
            self._engine.lag if self._engine is not None
            else (self.resolved_pipeline_lag()
                  if self.pipeline_enabled else None)
        )
        reg = get_registry()
        reg.gauge("worker.pipeline_lag").set(lag)
        reg.gauge("worker.pipeline_degraded").set(self.pipeline_degraded)
        reg.gauge("worker.matches_per_sec").set(round(self.matches_per_sec, 1))
        return {
            "matches_rated": self.matches_rated,
            "batches_ok": self.batches_ok,
            "batches_failed": self.batches_failed,
            "dead_letters": self.dead_letters,
            "matches_per_sec": round(self.matches_per_sec, 1),
            "pipeline_enabled": self.pipeline_enabled,
            "pipeline_degraded": self.pipeline_degraded,
            "pipeline_engine_failures": self.pipeline_engine_failures,
            "pipeline_lag": lag,
            # The same number under the name the engine resolves it by —
            # operators correlate this against PIPELINE_LAG/probe logs.
            "resolved_pipeline_lag": lag,
            "measured_rtt_ms": (
                round(self.measured_rtt_s * 1e3, 1)
                if self.measured_rtt_s is not None else None
            ),
            "measured_host_ms": (
                round(self.measured_host_s * 1e3, 1)
                if self.measured_host_s is not None else None
            ),
            # The serving plane's keys ride along even when serving is
            # off (None) — scrapers key on presence, not worker flavor.
            "serve": (
                self.query_engine.stats()
                if self.query_engine is not None else None
            ),
            # The migration block (ROADMAP item 4's "progress exposed on
            # /statusz"): None until a backfill has run in this process,
            # else phase, lineage versions, watermark/progress % and the
            # history-ring-derived ETA (analyzer_tpu/migrate/progress.py).
            "migration": self._migration_block(),
            # The live SLO plane's digest (None when slo_plane=False):
            # what's burning, plus the shadow audit's counters when
            # auditing is on — /sloz and /historyz carry the detail.
            "slo": (
                {
                    "burning": self.watchdog.burning,
                    "history_samples": self.history.samples,
                    "audit": (
                        self.auditor.stats()
                        if self.auditor is not None else None
                    ),
                }
                if self.watchdog is not None else None
            ),
            # The rating-quality plane's digest (None when the ledger
            # is off): matches scored against their pre-update win
            # probability, running brier/ece, drift PSI — /qualityz
            # carries the full reliability table (obs/quality.py).
            "quality": (
                self.quality.stats() if self.quality is not None else None
            ),
            # Fabric membership (None off-fabric): the directory's
            # /statusz block — host index, owned shards, the fleet's
            # (host, shards, version) vector with down-ness.
            "fabric": (
                self.fabric_info() if self.fabric_info is not None else None
            ),
        }

    def _migration_block(self) -> dict | None:
        """The ``stats()['migration']`` block: the process-wide migration
        progress record, with the ETA derived from THIS worker's history
        rings and clock (virtual under the soak). None when no migration
        has run — scrapers key on presence, not on worker flavor."""
        from analyzer_tpu.migrate.progress import get_migration_progress

        return get_migration_progress().snapshot(
            history=self.history, now=self.clock()
        )

    @property
    def pipeline_degraded(self) -> bool:
        """True while a pipeline-configured worker is routing batches
        through the sequential loop — a permanent clone refusal flipped
        ``pipeline_enabled`` off, or a transient engine-construction
        failure is inside its retry window. False before the first flush
        (the engine is built lazily) and in sequential-by-config
        workers. A metrics surface for the state ADVICE r4 flagged as
        one-log-line-and-silent."""
        return self._pipeline_requested and (
            not self.pipeline_enabled or self._engine_retry_at is not None
        )


def requeue_failed(
    broker, config: "ServiceConfig",
    empty_polls: int = 5, poll_interval: float = 0.2,
    sleep=time.sleep,
) -> int:
    """Redrives every dead-lettered message from ``<QUEUE>_failed`` back
    onto the main queue, headers intact. Returns the count.

    The operational complement to the failure policy: after fixing the
    cause (schema, upstream data, a poison record), the reference's
    operators had to shovel `analyze_failed` back by hand with broker
    tooling; here it is one command (`cli worker --requeue-failed`).

    Broker realities this respects:
      * both queues are declared first — subscribing to a missing queue
        404s a real channel, and publishing to a missing main queue
        would silently DROP the redriven messages;
      * a push-consumer broker (the pika adapter) returns empty from its
        first non-blocking polls while the server's deliveries are in
        flight, so the drain only stops after ``empty_polls`` CONSECUTIVE
        empty polls ``poll_interval`` apart;
      * delivery is at-least-once: each message re-publishes BEFORE its
        ack, so a crash or connection blip mid-drain can duplicate up to
        one prefetch window, never lose — and rating is idempotent per
        match (a re-rate writes the same posteriors)."""
    broker.declare_queue(config.queue)
    broker.declare_queue(config.failed_queue)
    moved = 0
    empties = 0
    while empties < empty_polls:
        batch = broker.get(config.failed_queue, 100)
        if not batch:
            empties += 1
            sleep(poll_interval)
            continue
        empties = 0
        for msg in batch:
            broker.publish(config.queue, msg.body, msg.headers)
            broker.ack(msg.delivery_tag)
            moved += 1
    logger.info(
        "requeued %d dead-lettered message(s) %s -> %s",
        moved, config.failed_queue, config.queue,
    )
    return moved


def main(
    max_flushes: int | None = None,
    obs_port: int | None = None,
    flight_dir: str | None = None,
    serve_port: int | None = None,
    serve_shards: int | None = None,
    profile_dir: str | None = None,
    audit: bool | None = None,
    slo_plane: bool = True,
) -> Worker:
    """``python -m analyzer_tpu.service.worker`` — the reference's
    ``python3 worker.py`` entry point (``worker.py:219-221``), requiring a
    live RabbitMQ (pika installed) to be useful. Embedded/in-process use
    goes through Worker(InMemoryBroker(), InMemoryStore()) instead.
    ``max_flushes`` bounds the consume loop (tests; None = forever like
    the reference's ``start_consuming``; bounded runs get a 60 s
    wall-clock deadline so they fail loudly rather than spin). Returns
    the Worker for inspection after a bounded run.

    ``obs_port`` (or ``ANALYZER_TPU_OBS_PORT``) starts obsd;
    ``flight_dir`` (or ``ANALYZER_TPU_FLIGHT_DIR``) arms flight-recorder
    dumps; ``serve_port`` (or ``ANALYZER_TPU_SERVE_PORT``) starts the
    ratesrv query-serving plane (docs/serving.md); ``serve_shards`` (or
    ``ANALYZER_TPU_SERVE_SHARDS``) > 1 serves through the sharded plane
    (ShardedViewPublisher + ShardedQueryEngine — bit-identical results,
    docs/serving.md "Sharded plane"); ``profile_dir`` (or
    ``ANALYZER_TPU_PROFILE_DIR``) arms on-demand jax.profiler capture
    windows — SIGUSR2, automatic on dead-letter/degradation
    (docs/observability.md "Device-time attribution"); ``audit`` (or
    ``ANALYZER_TPU_AUDIT=1``) turns on the continuous shadow audit of
    served queries against the bit-exact oracle; ``slo_plane=False``
    disables the history sampler + SLO watchdog + audit entirely
    (docs/observability.md "History rings / SLO engine / Shadow
    audit")."""
    config = ServiceConfig.from_env()
    if obs_port is None and os.environ.get("ANALYZER_TPU_OBS_PORT"):
        obs_port = int(os.environ["ANALYZER_TPU_OBS_PORT"])
    if serve_port is None and os.environ.get("ANALYZER_TPU_SERVE_PORT"):
        serve_port = int(os.environ["ANALYZER_TPU_SERVE_PORT"])
    if serve_shards is None and os.environ.get("ANALYZER_TPU_SERVE_SHARDS"):
        serve_shards = int(os.environ["ANALYZER_TPU_SERVE_SHARDS"])
    from analyzer_tpu.service.broker import make_pika_broker

    # Sequential mode: prefetch_count=BATCHSIZE bounds in-flight messages
    # exactly like the reference (worker.py:91). Pipelined mode widens it
    # to cover the in-flight window — the pipeline defers acks until a
    # batch's commit is harvested, and a one-batch bound would make the
    # broker withhold batch N+1 until batch N fully acked, serializing
    # the loop back to sequential (ServiceConfig.prefetch_count).
    broker = make_pika_broker(
        config.rabbitmq_uri, prefetch=config.prefetch_count
    )
    if config.database_uri:
        from analyzer_tpu.service.sql_store import SqlStore

        store = SqlStore(config.database_uri, chunk_size=config.chunk_size)
    else:
        from analyzer_tpu.service.store import InMemoryStore

        store = InMemoryStore()
    worker = Worker(
        broker, store, config, obs_port=obs_port, flight_dir=flight_dir,
        serve_port=serve_port, serve_shards=serve_shards,
        profile_dir=profile_dir, audit=audit, slo_plane=slo_plane,
    )
    worker.warmup()  # compile before consuming: no first-batch stall
    try:
        worker.run(
            max_flushes=max_flushes,
            max_wall_s=None if max_flushes is None else 60.0,
            # Production loop: SIGTERM/SIGINT finish the in-flight batch
            # (commit + acks) before exiting; bounded test runs skip the
            # handler install (may run off the main thread).
            install_signal_handlers=max_flushes is None,
        )
    finally:
        worker.close()  # writer thread + cloned store connection
    return worker


if __name__ == "__main__":
    main()
