"""ctypes loader for the native columnar sqlite scanner (fastsql.cc).

Compiled/loaded via the shared helper (``analyzer_tpu.native_build``):
ImportError on ANY build or load failure so callers' pure-python bulk
scans engage instead. ``fastsql.cc`` itself dlopens ``libsqlite3.so.0``
at first use — a host without the library fails at call time, which the
wrapper converts to RuntimeError for the same fallback treatment.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from analyzer_tpu.native_build import build_and_load

_DIR = os.path.dirname(os.path.abspath(__file__))
_lib = build_and_load(
    os.path.join(_DIR, "fastsql.cc"), os.path.join(_DIR, "_fastsql.so")
)
_lib.sq_scan_open.argtypes = [
    ctypes.c_char_p,                  # db path
    ctypes.c_char_p,                  # sql
    ctypes.c_int32,                   # ncols
    ctypes.POINTER(ctypes.c_int32),   # spec
    ctypes.c_char_p,                  # err
    ctypes.c_int32,                   # errlen
]
_lib.sq_scan_open.restype = ctypes.c_void_p
_lib.sq_scan_nrows.argtypes = [ctypes.c_void_p]
_lib.sq_scan_nrows.restype = ctypes.c_int64
_lib.sq_scan_width.argtypes = [ctypes.c_void_p, ctypes.c_int32]
_lib.sq_scan_width.restype = ctypes.c_int64
_lib.sq_scan_copy.argtypes = [
    ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p, ctypes.c_int64,
]
_lib.sq_scan_copy.restype = ctypes.c_int32
_lib.sq_scan_free.argtypes = [ctypes.c_void_p]
_lib.sq_scan_free.restype = None
_lib.sq_cumcount.argtypes = [
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64),
]
_lib.sq_cumcount.restype = ctypes.c_int32
_lib.sq_lookup.argtypes = [
    ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
    ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
    ctypes.POINTER(ctypes.c_int64),
]
_lib.sq_lookup.restype = ctypes.c_int32

_KIND = {"str": 0, "int": 1, "float": 2}
_ERRLEN = 512


def scan_query(path: str, sql: str, cols: list[tuple[str, str]]) -> dict:
    """Runs ``sql`` (read-only, by path — committed data only, like the
    python bulk path's second connection) and returns ``{name: array}``:
    fixed-width bytes (``S``) for ``"str"`` columns, int64 for ``"int"``
    (NULL -> 0), float64 for ``"float"`` (NULL -> NaN) — the exact dtype
    and NULL conventions of ``SqlStore._sqlite_bulk``.

    One pass over the query: the C side buffers each column (string
    values in a byte arena) and numpy arrays fill by memcpy. Raises
    RuntimeError on any sqlite error; callers fall back to the python
    scan.
    """
    spec = np.array([_KIND[k] for _, k in cols], np.int32)
    err = ctypes.create_string_buffer(_ERRLEN)
    h = _lib.sq_scan_open(
        path.encode(), sql.encode(), len(cols),
        spec.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), err, _ERRLEN,
    )
    if not h:
        raise RuntimeError(f"native sqlite scan failed: {err.value.decode()}")
    try:
        n = _lib.sq_scan_nrows(h)
        arrays: dict[str, np.ndarray] = {}
        for c, (name, kind) in enumerate(cols):
            if kind == "str":
                width = max(int(_lib.sq_scan_width(h, c)), 1)
                a = np.empty(n, dtype=f"S{width}")
            elif kind == "int":
                a = np.empty(n, np.int64)
            else:
                a = np.empty(n, np.float64)
            if n:
                rc = _lib.sq_scan_copy(
                    h, c, ctypes.c_void_p(a.ctypes.data), a.dtype.itemsize
                )
                if rc != 0:
                    raise RuntimeError(
                        f"native sqlite scan: copy failed for column {name}"
                    )
            arrays[name] = a
        return arrays
    finally:
        _lib.sq_scan_free(h)


def cumcount(keys: np.ndarray, minlength: int) -> np.ndarray:
    """Arrival-order occurrence index within each key group (the numpy
    version needs a stable argsort + segmented arange). ``keys`` must be
    int64 in ``[0, minlength)`` — the C loop now enforces the bound per
    element (rc=-2) instead of trusting the caller, so a future caller
    that violates it raises here (and sql_store falls back to the numpy
    path) rather than silently corrupting heap memory."""
    keys = np.ascontiguousarray(keys, np.int64)
    out = np.empty(keys.size, np.int64)
    if keys.size == 0:
        return out
    rc = _lib.sq_cumcount(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), keys.size,
        int(minlength), out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc == -2:
        raise RuntimeError(
            "native cumcount: key outside [0, minlength) — caller bug"
        )
    if rc != 0:
        raise RuntimeError("native cumcount: counter allocation failed")
    return out


def lookup(keys: np.ndarray, needles: np.ndarray) -> np.ndarray:
    """Vectorized id join: index of each ``needle`` in ``keys`` (both
    fixed-width ``S`` arrays), -1 for misses; duplicate keys resolve to
    the smallest index — exactly numpy's stable argsort + searchsorted-
    left join, but via an FNV-1a hash table in C (the numpy version costs
    ~4.3 s at the 7.3M-needle scale, this a few hundred ms). Returns
    int64 ``[len(needles)]``.
    """
    assert keys.dtype.kind == "S" and needles.dtype.kind == "S"
    keys = np.ascontiguousarray(keys)
    needles = np.ascontiguousarray(needles)
    out = np.empty(needles.size, np.int64)
    if needles.size == 0:
        return out
    if keys.size == 0:
        out.fill(-1)
        return out
    rc = _lib.sq_lookup(
        ctypes.c_char_p(keys.ctypes.data), keys.dtype.itemsize, keys.size,
        ctypes.c_char_p(needles.ctypes.data), needles.dtype.itemsize,
        needles.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    if rc != 0:
        raise RuntimeError("native id join: hash table allocation failed")
    return out
