"""Message broker edge: in-memory queues plus an optional pika adapter.

The reference talks to RabbitMQ through pika 0.10's blocking API
(``worker.py:85-92``): durable queues, bounded prefetch, per-message
ack/nack, publish with headers. This module models exactly the subset the
worker needs, with an in-memory implementation for tests/embedded use and
a pika adapter that activates only when pika is importable (it is not a
baked dependency of this framework).
"""

from __future__ import annotations

import dataclasses
import itertools
import zlib
from collections import deque
from typing import Iterator, Protocol

from analyzer_tpu.obs import get_registry


@dataclasses.dataclass
class Message:
    body: bytes
    headers: dict | None = None
    delivery_tag: int = 0


class Broker(Protocol):
    def declare_queue(self, name: str) -> None: ...

    def publish(self, queue: str, body: bytes, headers: dict | None = None) -> None: ...

    def publish_topic(
        self, exchange: str, routing_key: str, body: bytes
    ) -> None: ...

    def get(self, queue: str, limit: int) -> list[Message]: ...

    def ack(self, delivery_tag: int) -> None: ...

    def nack(self, delivery_tag: int, requeue: bool = False) -> None: ...

    def qsize(self, queue: str) -> int:
        """Best-effort ready-message depth of ``queue`` (excluding
        in-flight/unacked deliveries). Both shipped brokers have always
        had queue-depth access — the Protocol just omitted it, so the
        soak harness and the worker's ``broker.queue_depth`` gauge had
        nothing typed to call. The number is a SNAPSHOT (on AMQP it
        costs a passive-declare round trip), for backpressure
        visibility, never for control flow."""
        ...


class InMemoryBroker:
    """Queues as deques with unacked-message redelivery semantics: ``get``
    moves messages to an in-flight map; ``nack(requeue=True)`` or
    ``requeue_unacked`` (crash simulation) returns them, ``ack`` drops them
    — the delivery contract the reference relies on for crash recovery
    (SURVEY.md section 5.3)."""

    def __init__(self) -> None:
        self.queues: dict[str, deque[Message]] = {}
        self.topics: list[tuple[str, str, bytes]] = []
        self._unacked: dict[int, tuple[str, Message]] = {}
        self._tags = itertools.count(1)

    def declare_queue(self, name: str) -> None:
        self.queues.setdefault(name, deque())

    def publish(self, queue: str, body: bytes, headers: dict | None = None) -> None:
        self.declare_queue(queue)
        self.queues[queue].append(Message(body=body, headers=dict(headers or {})))

    def publish_topic(self, exchange: str, routing_key: str, body: bytes) -> None:
        self.topics.append((exchange, routing_key, body))

    def get(self, queue: str, limit: int) -> list[Message]:
        self.declare_queue(queue)
        out = []
        q = self.queues[queue]
        while q and len(out) < limit:
            msg = q.popleft()
            msg = dataclasses.replace(msg, delivery_tag=next(self._tags))
            self._unacked[msg.delivery_tag] = (queue, msg)
            out.append(msg)
        return out

    def ack(self, delivery_tag: int) -> None:
        self._unacked.pop(delivery_tag, None)

    def nack(self, delivery_tag: int, requeue: bool = False) -> None:
        entry = self._unacked.pop(delivery_tag, None)
        if entry and requeue:
            queue, msg = entry
            self.queues[queue].appendleft(msg)

    def requeue_unacked(self) -> None:
        """Simulates a consumer crash: the broker redelivers everything."""
        for queue, msg in list(self._unacked.values()):
            self.queues[queue].appendleft(msg)
        self._unacked.clear()

    def set_prefetch(self, prefetch: int) -> None:
        """No delivery bound to adjust in memory; recorded for tests."""
        self.prefetch = int(prefetch)

    def qsize(self, queue: str) -> int:
        return len(self.queues.get(queue, ()))


#: Priority-lane names (docs/ingest.md "Lane arbitration"): live match
#: traffic always outranks backfill/replay; the admission controller
#: decides how much backfill the host has headroom for.
LANE_LIVE = "live"
LANE_BACKFILL = "backfill"
_LANES = (LANE_LIVE, LANE_BACKFILL)


def partition_of(body: bytes, headers: dict | None, partitions: int) -> int:
    """The partition routing function. Publishers that know the match's
    home shard set an ``x-partition`` header from the mesh layout
    invariant (``row % S`` of a participating player — the same function
    the serve plane routes lookups by); headerless messages hash the
    body (crc32 — stable across processes and runs, unlike ``hash()``)
    so partitioning never depends on publisher cooperation."""
    if headers and "x-partition" in headers:
        return int(headers["x-partition"]) % partitions
    return zlib.crc32(body) % partitions


class AdmissionController:
    """Decides how many backfill messages a consumer poll may admit
    (docs/ingest.md "Lane arbitration").

    Strict live priority: any ready live message zeroes the backfill
    quota. With live drained, admission is gated on HOST headroom, read
    from the telemetry the pipeline already emits: a growing
    ``feed.starved_total`` means the device is outrunning the host —
    adding backfill decode/encode work would push live latency up — and
    a burst of ``tier.promotions_total`` means the H2D lane is busy
    moving hot-set pages, the same bandwidth a backfill batch's
    transfers would contend with. Either signal halves the open window
    instead of closing it (backfill must not starve forever); quiet
    telemetry admits the full remaining window. Decisions are pure
    functions of counter deltas, so a soak's admission sequence is
    deterministic per (seed, config)."""

    def __init__(
        self,
        registry=None,
        starve_threshold: int = 1,
        promote_threshold: int = 256,
    ) -> None:
        self._registry = registry
        self.starve_threshold = int(starve_threshold)
        self.promote_threshold = int(promote_threshold)
        self._last_starved: float | None = None
        self._last_promotes: float | None = None

    def quota(self, live_ready: int, limit: int) -> int:
        """Backfill messages admissible now, given ``live_ready`` live
        messages still waiting and ``limit`` slots of consumer room."""
        if limit <= 0:
            return 0
        reg = self._registry or get_registry()
        starved = reg.counter("feed.starved_total").value
        promotes = reg.counter("tier.promotions_total").value
        d_starved = (
            0.0 if self._last_starved is None else starved - self._last_starved
        )
        d_promotes = (
            0.0 if self._last_promotes is None
            else promotes - self._last_promotes
        )
        self._last_starved = starved
        self._last_promotes = promotes
        if live_ready > 0:
            return 0
        if (
            d_starved >= self.starve_threshold
            or d_promotes >= self.promote_threshold
        ):
            return max(1, limit // 2)
        return limit


class PartitionedBroker:
    """In-memory broker partitioned by player-shard with priority lanes
    — the wire-speed ingest edge (docs/ingest.md "Partition math").

    Each logical queue is ``partitions`` x ``(live, backfill)`` physical
    deques. Publish routes by :func:`partition_of` and stamps a
    per-logical-queue sequence number; ``get`` k-way-merges partition
    heads by that sequence, so with live-only traffic the delivery
    order — and every delivery tag — is EXACTLY
    :class:`InMemoryBroker`'s for the same publish sequence. That is
    the soak bit-identity contract: partitioning changes where messages
    WAIT (per-partition depth, backpressure, dead-letter attribution),
    never what order they are consumed in. Lanes are the one sanctioned
    reordering: backfill is admitted behind live by the
    :class:`AdmissionController`.

    Dead-lettering inherits partitioning for free: the worker
    republishes a poison message to ``<queue>_failed`` with its
    original headers, so the failed queue's per-partition depths name
    WHICH shard's traffic is poisoned (``partition_depths``).

    On AMQP the same layout maps to ``<queue>.p<k>`` physical queues;
    this in-memory implementation is the contract the adapter would
    have to meet (per-partition ``message_count``, seq-merged delivery).
    """

    def __init__(
        self,
        partitions: int = 1,
        lanes: bool = False,
        admission: AdmissionController | None = None,
    ) -> None:
        if partitions < 1:
            raise ValueError(f"partitions must be >= 1, got {partitions}")
        self.partitions = int(partitions)
        self.lanes = bool(lanes)
        self.admission = admission or (AdmissionController() if lanes else None)
        # queue -> [partition][lane] -> deque[(seq, Message)]
        self.queues: dict[str, list[dict[str, deque]]] = {}
        self.topics: list[tuple[str, str, bytes]] = []
        self._seq: dict[str, itertools.count] = {}
        self._unacked: dict[int, tuple[str, int, str, int, Message]] = {}
        self._tags = itertools.count(1)
        reg = get_registry()
        reg.gauge("broker.partitions").set(self.partitions)
        self._admitted = reg.counter("broker.backfill_admitted_total")
        self._throttled = reg.counter("broker.backfill_throttled_total")

    def declare_queue(self, name: str) -> None:
        if name not in self.queues:
            self.queues[name] = [
                {lane: deque() for lane in _LANES}
                for _ in range(self.partitions)
            ]
            self._seq[name] = itertools.count()

    def publish(self, queue: str, body: bytes, headers: dict | None = None) -> None:
        self.declare_queue(queue)
        h = dict(headers or {})
        lane = h.get("x-lane", LANE_LIVE) if self.lanes else LANE_LIVE
        if lane not in _LANES:
            lane = LANE_LIVE
        p = partition_of(body, h, self.partitions)
        self.queues[queue][p][lane].append(
            (next(self._seq[queue]), Message(body=body, headers=h))
        )

    def publish_topic(self, exchange: str, routing_key: str, body: bytes) -> None:
        self.topics.append((exchange, routing_key, body))

    def _pop_merged(
        self,
        queue: str,
        lane: str,
        limit: int,
        out: list,
        partitions=None,
    ) -> None:
        """Moves up to ``limit - len(out)`` messages of ``lane`` into
        ``out`` in global sequence order (smallest head across the
        partitions first — requeued messages keep their original seq,
        so a redelivery outranks everything published after it).
        ``partitions`` restricts the merge to a subset of partition
        indices (a fabric worker's owned frontier); None means all."""
        parts = self.queues[queue]
        span = range(self.partitions) if partitions is None else partitions
        while len(out) < limit:
            best = None
            for p in span:
                q = parts[p][lane]
                if q and (best is None or q[0][0] < parts[best][lane][0][0]):
                    best = p
            if best is None:
                return
            seq, msg = parts[best][lane].popleft()
            msg = dataclasses.replace(msg, delivery_tag=next(self._tags))
            self._unacked[msg.delivery_tag] = (queue, best, lane, seq, msg)
            out.append(msg)

    def get(self, queue: str, limit: int, partitions=None) -> list[Message]:
        self.declare_queue(queue)
        out: list[Message] = []
        self._pop_merged(queue, LANE_LIVE, limit, out, partitions)
        room = limit - len(out)
        if self.lanes and room > 0:
            live_left = self.lane_size(queue, LANE_LIVE, partitions)
            quota = (
                self.admission.quota(live_left, room)
                if self.admission is not None else room
            )
            quota = min(quota, room)
            before = len(out)
            self._pop_merged(
                queue, LANE_BACKFILL, before + quota, out, partitions
            )
            admitted = len(out) - before
            if admitted:
                self._admitted.add(admitted)
            waiting = self.lane_size(queue, LANE_BACKFILL, partitions)
            if waiting and quota < room:
                self._throttled.add(min(waiting, room - quota))
        return out

    def ack(self, delivery_tag: int) -> None:
        self._unacked.pop(delivery_tag, None)

    def nack(self, delivery_tag: int, requeue: bool = False) -> None:
        entry = self._unacked.pop(delivery_tag, None)
        if entry and requeue:
            queue, p, lane, seq, msg = entry
            self.queues[queue][p][lane].appendleft((seq, msg))

    def requeue_unacked(self) -> None:
        """Simulates a consumer crash: the broker redelivers everything
        (each message back at its partition/lane head, original seq —
        the merge restores global order). Returned highest-seq-first so
        every deque stays seq-ascending head to tail."""
        entries = sorted(self._unacked.values(), key=lambda e: -e[3])
        for queue, p, lane, seq, msg in entries:
            self.queues[queue][p][lane].appendleft((seq, msg))
        self._unacked.clear()

    def set_prefetch(self, prefetch: int) -> None:
        """No delivery bound to adjust in memory; recorded for tests."""
        self.prefetch = int(prefetch)

    def lane_size(self, queue: str, lane: str, partitions=None) -> int:
        """Ready depth of one lane across every partition (or the given
        subset of partition indices)."""
        parts = self.queues.get(queue)
        if parts is None:
            return 0
        span = range(self.partitions) if partitions is None else partitions
        return sum(len(parts[p][lane]) for p in span)

    def qsize(self, queue: str, partitions=None) -> int:
        """AGGREGATE ready depth across all partitions and lanes — the
        number a single-queue broker would report, so existing
        ``broker.queue_depth`` consumers (worker gauge, soak sampler)
        keep meaning the same thing."""
        return sum(self.lane_size(queue, lane, partitions) for lane in _LANES)

    def partition_depths(self, queue: str) -> dict[int, dict[str, int]]:
        """Per-partition, per-lane ready depths — the skew surface the
        worker samples into ``broker.queue_depth{queue=,partition=,
        lane=}`` series (bounded by the registry's label-cardinality
        cap) and /statusz renders for the hot-partition runbook."""
        parts = self.queues.get(queue)
        if parts is None:
            return {}
        return {
            p: {lane: len(parts[p][lane]) for lane in _LANES}
            for p in range(self.partitions)
        }


def physical_queue(queue: str, partition: int, lane: str) -> str:
    """The partition x lane -> physical AMQP queue naming contract
    (docs/ingest.md "Partition math"): logical queue ``q`` with ``P``
    partitions and priority lanes maps onto ``q.p<k>.{live,backfill}``
    physical queues. The in-memory :class:`PartitionedBroker` documents
    the delivery semantics this layout must reproduce; the adapter that
    reproduces them over any real broker is
    :class:`AmqpPartitionedBroker`."""
    return f"{queue}.p{partition}.{lane}"


class AmqpPartitionedBroker:
    """:class:`PartitionedBroker`'s layout mapped onto PHYSICAL queues of
    an underlying broker — the backfill lane on a real AMQP server.

    ``base`` is any :class:`Broker` (the pika adapter in production; an
    :class:`InMemoryBroker` standing in for the AMQP server under test —
    the stub-backed parity suite, tests/test_migrate.py). Every logical
    queue becomes ``partitions x 2`` physical queues named by
    :func:`physical_queue`; publish routes by :func:`partition_of` and
    the ``x-lane`` header and stamps a per-logical-queue ``x-seq``
    header, and ``get`` k-way-merges the partition heads by that seq —
    live lane first, backfill admitted behind it by the
    :class:`AdmissionController`, exactly the in-memory contract.

    Two honest deviations from the in-memory broker, both inherent to a
    real server: (1) the seq merge is exact over messages the server has
    DELIVERED — a partition whose smaller-seq message is still in
    network flight can be overtaken within one poll (at-least-once
    consumers already tolerate reordering at that granularity); (2)
    ``x-seq`` is stamped per publishing process — multiple publishers
    interleave by arrival, like any AMQP fan-in. Messages with no
    ``x-seq`` (a foreign publisher) merge by arrival order.

    Delivery tags are the base broker's own, so ack/nack/redelivery
    semantics — including the pika adapter's reconnect discipline —
    pass straight through.
    """

    def __init__(
        self,
        base,
        partitions: int = 1,
        lanes: bool = False,
        admission: AdmissionController | None = None,
    ) -> None:
        if partitions < 1:
            raise ValueError(f"partitions must be >= 1, got {partitions}")
        self.base = base
        self.partitions = int(partitions)
        self.lanes = bool(lanes)
        self.admission = admission or (AdmissionController() if lanes else None)
        self._declared: set[str] = set()
        self._seq: dict[str, itertools.count] = {}
        self._arrival = itertools.count(1 << 60)  # foreign-publisher order
        # (logical queue, partition, lane) -> locally buffered heads
        # (pulled from the base broker, not yet merged out).
        self._heads: dict[tuple, deque[Message]] = {}
        reg = get_registry()
        reg.gauge("broker.partitions").set(self.partitions)
        self._admitted = reg.counter("broker.backfill_admitted_total")
        self._throttled = reg.counter("broker.backfill_throttled_total")

    def _lanes_of(self) -> tuple:
        return _LANES if self.lanes else (LANE_LIVE,)

    def declare_queue(self, name: str) -> None:
        if name in self._declared:
            return
        self._declared.add(name)
        self._seq.setdefault(name, itertools.count())
        for p in range(self.partitions):
            for lane in _LANES:
                # Both lanes always exist physically: a backfill
                # publisher must never race queue creation mid-migration.
                self.base.declare_queue(physical_queue(name, p, lane))

    def publish(self, queue: str, body: bytes, headers: dict | None = None) -> None:
        self.declare_queue(queue)
        h = dict(headers or {})
        lane = h.get("x-lane", LANE_LIVE) if self.lanes else LANE_LIVE
        if lane not in _LANES:
            lane = LANE_LIVE
        p = partition_of(body, h, self.partitions)
        h["x-seq"] = next(self._seq[queue])
        self.base.publish(physical_queue(queue, p, lane), body, headers=h)

    def publish_topic(self, exchange: str, routing_key: str, body: bytes) -> None:
        self.base.publish_topic(exchange, routing_key, body)

    def _head(self, queue: str, p: int, lane: str) -> deque:
        return self._heads.setdefault((queue, p, lane), deque())

    def _pull(self, queue: str, lane: str, limit: int, partitions=None) -> None:
        """Tops up each partition's local head buffer from the base
        broker so the merge can see every partition's frontier. Each
        buffer is kept seq-sorted: a nacked-with-requeue message
        re-enters at the BASE queue's head, so a later pull can hand it
        back while larger-seq messages already sit buffered — the sort
        restores the per-partition ascending order the k-way merge
        assumes (a redelivery outranks everything published after it,
        the in-memory broker's contract). ``partitions`` restricts the
        pull to a subset of partition indices; None means all."""
        span = range(self.partitions) if partitions is None else partitions
        for p in span:
            buf = self._head(queue, p, lane)
            want = limit - len(buf)
            if want > 0:
                got = self.base.get(physical_queue(queue, p, lane), want)
                if got:
                    buf.extend(got)
                    if len(buf) > len(got) or len(got) > 1:
                        ordered = sorted(buf, key=self._seq_of)
                        buf.clear()
                        buf.extend(ordered)

    def _seq_of(self, msg: Message) -> int:
        seq = (msg.headers or {}).get("x-seq")
        if seq is None:
            # Foreign publisher: assign (and STAMP — the number must be
            # stable across repeated sorts/merges) an arrival-order seq.
            seq = next(self._arrival)
            if msg.headers is None:
                msg.headers = {}
            msg.headers["x-seq"] = seq
        return int(seq)

    def _pop_merged(
        self,
        queue: str,
        lane: str,
        limit: int,
        out: list,
        partitions=None,
    ) -> None:
        """Moves up to ``limit - len(out)`` buffered messages of ``lane``
        into ``out`` in global x-seq order (smallest head across the
        partitions first) — the in-memory broker's merge, over the
        heads the server has delivered."""
        self._pull(queue, lane, limit, partitions)
        span = range(self.partitions) if partitions is None else partitions
        while len(out) < limit:
            best = None
            best_seq = None
            for p in span:
                buf = self._heads.get((queue, p, lane))
                if not buf:
                    continue
                seq = self._seq_of(buf[0])
                if best_seq is None or seq < best_seq:
                    best, best_seq = p, seq
            if best is None:
                return
            out.append(self._heads[(queue, best, lane)].popleft())

    def get(self, queue: str, limit: int, partitions=None) -> list[Message]:
        self.declare_queue(queue)
        out: list[Message] = []
        self._pop_merged(queue, LANE_LIVE, limit, out, partitions)
        room = limit - len(out)
        if self.lanes and room > 0:
            live_left = self.lane_size(queue, LANE_LIVE, partitions)
            quota = (
                self.admission.quota(live_left, room)
                if self.admission is not None else room
            )
            quota = min(quota, room)
            before = len(out)
            self._pop_merged(
                queue, LANE_BACKFILL, before + quota, out, partitions
            )
            admitted = len(out) - before
            if admitted:
                self._admitted.add(admitted)
            waiting = self.lane_size(queue, LANE_BACKFILL, partitions)
            if waiting and quota < room:
                self._throttled.add(min(waiting, room - quota))
        return out

    def ack(self, delivery_tag: int) -> None:
        self.base.ack(delivery_tag)

    def nack(self, delivery_tag: int, requeue: bool = False) -> None:
        self.base.nack(delivery_tag, requeue=requeue)

    def requeue_unacked(self) -> None:
        """Crash simulation passthrough (stub-backed tests); a real AMQP
        base redelivers on channel death instead."""
        requeue = getattr(self.base, "requeue_unacked", None)
        if requeue is not None:
            requeue()

    def set_prefetch(self, prefetch: int) -> None:
        set_prefetch = getattr(self.base, "set_prefetch", None)
        if set_prefetch is not None:
            set_prefetch(int(prefetch))

    def lane_size(self, queue: str, lane: str, partitions=None) -> int:
        """Ready depth of one lane across every partition (or the given
        subset): the base broker's per-physical-queue depth plus locally
        buffered heads."""
        total = 0
        span = range(self.partitions) if partitions is None else partitions
        for p in span:
            total += self.base.qsize(physical_queue(queue, p, lane))
            total += len(self._heads.get((queue, p, lane), ()))
        return total

    def qsize(self, queue: str, partitions=None) -> int:
        """Aggregate ready depth across partitions and lanes — the same
        single number a one-queue broker reports (worker gauge, soak
        sampler)."""
        return sum(self.lane_size(queue, lane, partitions) for lane in _LANES)

    def partition_depths(self, queue: str) -> dict[int, dict[str, int]]:
        """Per-partition, per-lane ready depths — the /statusz skew
        surface, same shape as :meth:`PartitionedBroker.partition_depths`."""
        if queue not in self._declared:
            return {}
        return {
            p: {
                lane: (
                    self.base.qsize(physical_queue(queue, p, lane))
                    + len(self._heads.get((queue, p, lane), ()))
                )
                for lane in _LANES
            }
            for p in range(self.partitions)
        }


class PartitionSubscription:
    """A shard-owning worker's consumption window onto a partitioned
    broker (docs/fabric.md "Broker-partitioned ingest").

    In a fabric every host owns the shards ``s % n_hosts == host`` and,
    because ``partition_of == shard ownership`` (the publisher stamps
    ``x-partition`` with the match's home shard), exactly the same
    partitions. This wrapper implements the :class:`Broker` protocol
    over one broker with ``get``/depth restricted to those owned
    partition indices, so the :class:`~analyzer_tpu.service.worker.
    Worker` stays partition-blind: it consumes "a broker" and the
    subscription decides which physical frontier that means.

    Publish passes through UNRESTRICTED — a dead-letter republish to
    ``<queue>_failed`` keeps the message's original ``x-partition``
    header, so poison traffic stays attributed to the owning shard even
    when the republishing host does not own it. Ack/nack/prefetch pass
    straight through (delivery tags are the wrapped broker's own).
    """

    def __init__(self, broker, partitions) -> None:
        owned = tuple(sorted({int(p) for p in partitions}))
        if not owned:
            raise ValueError("subscription needs at least one partition")
        total = int(broker.partitions)
        for p in owned:
            if not 0 <= p < total:
                raise ValueError(
                    f"partition {p} outside the broker's 0..{total - 1}"
                )
        self.broker = broker
        self.owned = owned
        self.partitions = total  # the LOGICAL layout, not the window

    def declare_queue(self, name: str) -> None:
        self.broker.declare_queue(name)

    def publish(self, queue: str, body: bytes, headers: dict | None = None) -> None:
        self.broker.publish(queue, body, headers=headers)

    def publish_topic(self, exchange: str, routing_key: str, body: bytes) -> None:
        self.broker.publish_topic(exchange, routing_key, body)

    def get(self, queue: str, limit: int) -> list[Message]:
        return self.broker.get(queue, limit, partitions=self.owned)

    def ack(self, delivery_tag: int) -> None:
        self.broker.ack(delivery_tag)

    def nack(self, delivery_tag: int, requeue: bool = False) -> None:
        self.broker.nack(delivery_tag, requeue=requeue)

    def requeue_unacked(self) -> None:
        requeue = getattr(self.broker, "requeue_unacked", None)
        if requeue is not None:
            requeue()

    def set_prefetch(self, prefetch: int) -> None:
        set_prefetch = getattr(self.broker, "set_prefetch", None)
        if set_prefetch is not None:
            set_prefetch(int(prefetch))

    def lane_size(self, queue: str, lane: str) -> int:
        return self.broker.lane_size(queue, lane, self.owned)

    def qsize(self, queue: str) -> int:
        """Ready depth of the OWNED partitions only — the worker's
        ``broker.queue_depth`` gauge then reports this host's actual
        backlog, which is what per-host burn attribution wants."""
        return self.broker.qsize(queue, self.owned)

    def partition_depths(self, queue: str) -> dict[int, dict[str, int]]:
        full = self.broker.partition_depths(queue)
        return {p: d for p, d in full.items() if p in self.owned}


def make_partitioned_pika_broker(
    uri: str,
    partitions: int = 1,
    lanes: bool = False,
    prefetch: int = 0,
    admission: AdmissionController | None = None,
):
    """The production composition: :class:`AmqpPartitionedBroker` over
    the pika adapter — ``<queue>.p<k>.{live,backfill}`` physical queues
    on a real RabbitMQ, with the in-memory broker's partition/lane
    delivery contract. Raises ImportError when pika is absent, like
    :func:`make_pika_broker`."""
    return AmqpPartitionedBroker(
        make_pika_broker(uri, prefetch=prefetch),
        partitions=partitions,
        lanes=lanes,
        admission=admission,
    )


def make_pika_broker(uri: str, prefetch: int = 0):
    """RabbitMQ adapter; raises ImportError when pika is absent.

    PUSH consumer with bounded prefetch and reconnect. The reference's
    broker edge is ``basic_qos(prefetch_count=BATCHSIZE)`` +
    ``basic_consume`` (``worker.py:91-92``): the server pushes up to
    ``prefetch`` unacked messages in one flow. The round-2 adapter
    instead issued one synchronous ``basic_get`` round-trip per message
    (500 network RTTs per batch) and never set QoS (VERDICT round-2
    missing #1). Here ``get()`` just pumps the ioloop non-blocking and
    drains a local buffer the consumer callback fills.

    Reconnect: on a connection/channel error, any operation reconnects
    once — new connection, durable queues redeclared, QoS re-applied,
    consumers re-subscribed (the reference has none of this; it dies).
    Deliveries that were buffered but unacked die with the old channel —
    the broker requeues them, preserving the same at-least-once contract
    the reference leans on. Delivery tags handed to the caller are
    SYNTHETIC (monotonic across reconnects): an ack/nack for a message
    from a dead channel is a silent no-op (the message is redelivered),
    never an ack of the wrong message on the new channel.
    """
    from analyzer_tpu.logging_utils import get_logger

    import pika  # gated: not a baked dependency

    logger = get_logger(__name__)
    conn_errors = tuple(
        e
        for e in (
            getattr(pika.exceptions, name, None)
            for name in (
                "AMQPConnectionError", "AMQPChannelError", "ConnectionClosed",
                "ChannelClosed", "StreamLostError", "ChannelWrongStateError",
            )
        )
        if isinstance(e, type)
    ) or (ConnectionError,)

    class PikaBroker:
        def __init__(self, uri: str, prefetch: int) -> None:
            self._uri = uri
            self._prefetch = int(prefetch or 0)
            self._declared: list[str] = []
            self._consuming: list[str] = []
            self._consumer_tag: dict[str, object] = {}  # queue -> tag
            self._buf: dict[str, deque[Message]] = {}
            self._tags = itertools.count(1)
            self._live: dict[int, int] = {}  # synthetic -> channel tag
            self._connect()

        # -- connection lifecycle ----------------------------------------
        def _connect(self) -> None:
            self._conn = pika.BlockingConnection(pika.URLParameters(self._uri))
            self._ch = self._conn.channel()
            if self._prefetch:
                self._ch.basic_qos(prefetch_count=self._prefetch)
            for name in self._declared:
                self._ch.queue_declare(queue=name, durable=True)
            for queue in self._consuming:
                self._subscribe(queue)

        def _reconnect(self, err) -> None:
            logger.warning("pika connection lost (%s); reconnecting", err)
            # In-flight deliveries died with the channel; the broker
            # requeues them. Drop their local shadows so stale synthetic
            # tags can never ack a new-channel message.
            self._buf = {q: deque() for q in self._buf}
            self._live.clear()
            self._consumer_tag.clear()  # old channel's tags are invalid
            try:
                self._conn.close()
            except Exception:  # noqa: BLE001 — already dead is fine
                pass
            self._connect()

        def _retry(self, op):
            """Runs op; on connection loss reconnects once and re-runs.
            Only for idempotent-on-retry operations (declare, publish,
            pump) — acks go through _settle instead."""
            try:
                return op()
            except conn_errors as e:
                self._reconnect(e)
                return op()

        def _subscribe(self, queue: str) -> None:
            def on_message(_ch, method, properties, body, _q=queue):
                tag = next(self._tags)
                self._live[tag] = method.delivery_tag
                self._buf.setdefault(_q, deque()).append(
                    Message(
                        body=body,
                        headers=getattr(properties, "headers", None) or {},
                        delivery_tag=tag,
                    )
                )

            try:
                tag = self._ch.basic_consume(
                    queue=queue, on_message_callback=on_message
                )
            except TypeError:  # pika 0.10 legacy signature (the reference's pin)
                tag = self._ch.basic_consume(on_message, queue=queue)
            self._consumer_tag[queue] = tag

        # -- Broker protocol ---------------------------------------------
        def declare_queue(self, name: str) -> None:
            if name not in self._declared:
                self._declared.append(name)
            self._retry(
                lambda: self._ch.queue_declare(queue=name, durable=True)
            )

        def publish(self, queue: str, body: bytes, headers: dict | None = None) -> None:
            self._retry(
                lambda: self._ch.basic_publish(
                    "", queue, body, pika.BasicProperties(headers=headers or {})
                )
            )

        def publish_topic(self, exchange: str, routing_key: str, body: bytes) -> None:
            self._retry(
                lambda: self._ch.basic_publish(exchange, routing_key, body)
            )

        def get(self, queue: str, limit: int) -> list[Message]:
            if queue not in self._consuming:
                self._consuming.append(queue)
                try:
                    self._subscribe(queue)
                except conn_errors as e:
                    # NO retry of the op here: _connect re-subscribes
                    # everything in _consuming (including this queue) —
                    # re-running _subscribe would register a DUPLICATE
                    # consumer and silently double the per-consumer
                    # prefetch bound.
                    self._reconnect(e)
            # Pump the ioloop without blocking: the server pushes up to
            # the prefetch bound; the callback fills the buffer.
            self._retry(
                lambda: self._conn.process_data_events(time_limit=0)
            )
            buf = self._buf.setdefault(queue, deque())
            out: list[Message] = []
            while buf and len(out) < limit:
                out.append(buf.popleft())
            return out

        def _settle(self, delivery_tag: int, op) -> None:
            real = self._live.pop(delivery_tag, None)
            if real is None:
                return  # dead channel's tag: the broker redelivers it
            try:
                op(real)
            except conn_errors as e:
                # The settle is lost with the channel (at-least-once:
                # the message comes back); NEVER retry on the new
                # channel — the same numeric tag would settle a
                # different message there.
                self._reconnect(e)

        def set_prefetch(self, prefetch: int) -> None:
            """Re-bounds the per-consumer QoS window (and across
            reconnects). Used by a worker whose pipelined mode
            permanently degrades: the wide in-flight window sized for
            deferred acks would otherwise keep hogging deliveries a
            sequential consumer can't keep up with, starving healthy
            competing consumers on the same queue.

            RabbitMQ applies per-consumer (global=false) QoS at
            CONSUMER CREATION, so changing basic_qos alone would be a
            no-op for the live subscription — existing consumers are
            cancelled and re-registered under the new bound. Deliveries
            already buffered stay valid (their unacked window drains as
            the caller processes them)."""
            self._prefetch = int(prefetch or 0)

            def op():
                if self._prefetch:
                    self._ch.basic_qos(prefetch_count=self._prefetch)
                for queue, tag in list(self._consumer_tag.items()):
                    try:
                        self._ch.basic_cancel(tag)
                    except Exception:  # noqa: BLE001 — already-gone tag
                        pass
                    self._consumer_tag.pop(queue, None)
                for queue in self._consuming:
                    self._subscribe(queue)

            self._retry(op)

        def ack(self, delivery_tag: int) -> None:
            self._settle(delivery_tag, self._ch.basic_ack)

        def nack(self, delivery_tag: int, requeue: bool = False) -> None:
            self._settle(
                delivery_tag,
                lambda real: self._ch.basic_nack(real, requeue=requeue),
            )

        def qsize(self, queue: str) -> int:
            """Server-side ready depth (the passive-redeclare
            ``message_count`` snapshot) plus deliveries already pushed
            into the local buffer but not yet handed to the consumer —
            the caller-visible backlog. Older pika stubs return no
            declare result; those report the local buffer alone."""

            def op():
                res = self._ch.queue_declare(queue=queue, durable=True)
                method = getattr(res, "method", None)
                return int(getattr(method, "message_count", 0) or 0)

            return self._retry(op) + len(self._buf.get(queue, ()))

    return PikaBroker(uri, prefetch)
