"""Message broker edge: in-memory queues plus an optional pika adapter.

The reference talks to RabbitMQ through pika 0.10's blocking API
(``worker.py:85-92``): durable queues, bounded prefetch, per-message
ack/nack, publish with headers. This module models exactly the subset the
worker needs, with an in-memory implementation for tests/embedded use and
a pika adapter that activates only when pika is importable (it is not a
baked dependency of this framework).
"""

from __future__ import annotations

import dataclasses
import itertools
from collections import deque
from typing import Iterator, Protocol


@dataclasses.dataclass
class Message:
    body: bytes
    headers: dict | None = None
    delivery_tag: int = 0


class Broker(Protocol):
    def declare_queue(self, name: str) -> None: ...

    def publish(self, queue: str, body: bytes, headers: dict | None = None) -> None: ...

    def publish_topic(
        self, exchange: str, routing_key: str, body: bytes
    ) -> None: ...

    def get(self, queue: str, limit: int) -> list[Message]: ...

    def ack(self, delivery_tag: int) -> None: ...

    def nack(self, delivery_tag: int, requeue: bool = False) -> None: ...


class InMemoryBroker:
    """Queues as deques with unacked-message redelivery semantics: ``get``
    moves messages to an in-flight map; ``nack(requeue=True)`` or
    ``requeue_unacked`` (crash simulation) returns them, ``ack`` drops them
    — the delivery contract the reference relies on for crash recovery
    (SURVEY.md section 5.3)."""

    def __init__(self) -> None:
        self.queues: dict[str, deque[Message]] = {}
        self.topics: list[tuple[str, str, bytes]] = []
        self._unacked: dict[int, tuple[str, Message]] = {}
        self._tags = itertools.count(1)

    def declare_queue(self, name: str) -> None:
        self.queues.setdefault(name, deque())

    def publish(self, queue: str, body: bytes, headers: dict | None = None) -> None:
        self.declare_queue(queue)
        self.queues[queue].append(Message(body=body, headers=dict(headers or {})))

    def publish_topic(self, exchange: str, routing_key: str, body: bytes) -> None:
        self.topics.append((exchange, routing_key, body))

    def get(self, queue: str, limit: int) -> list[Message]:
        self.declare_queue(queue)
        out = []
        q = self.queues[queue]
        while q and len(out) < limit:
            msg = q.popleft()
            msg = dataclasses.replace(msg, delivery_tag=next(self._tags))
            self._unacked[msg.delivery_tag] = (queue, msg)
            out.append(msg)
        return out

    def ack(self, delivery_tag: int) -> None:
        self._unacked.pop(delivery_tag, None)

    def nack(self, delivery_tag: int, requeue: bool = False) -> None:
        entry = self._unacked.pop(delivery_tag, None)
        if entry and requeue:
            queue, msg = entry
            self.queues[queue].appendleft(msg)

    def requeue_unacked(self) -> None:
        """Simulates a consumer crash: the broker redelivers everything."""
        for queue, msg in list(self._unacked.values()):
            self.queues[queue].appendleft(msg)
        self._unacked.clear()

    def qsize(self, queue: str) -> int:
        return len(self.queues.get(queue, ()))


def make_pika_broker(uri: str):
    """RabbitMQ adapter; raises ImportError when pika is absent. Kept thin:
    the Worker only needs the 6-method Broker protocol."""
    import pika  # gated: not a baked dependency

    class PikaBroker:
        def __init__(self, uri: str) -> None:
            self._conn = pika.BlockingConnection(pika.URLParameters(uri))
            self._ch = self._conn.channel()

        def declare_queue(self, name: str) -> None:
            self._ch.queue_declare(queue=name, durable=True)

        def publish(self, queue: str, body: bytes, headers: dict | None = None) -> None:
            props = pika.BasicProperties(headers=headers or {})
            self._ch.basic_publish("", queue, body, props)

        def publish_topic(self, exchange: str, routing_key: str, body: bytes) -> None:
            self._ch.basic_publish(exchange, routing_key, body)

        def get(self, queue: str, limit: int):
            out = []
            for _ in range(limit):
                method, props, body = self._ch.basic_get(queue)
                if method is None:
                    break
                out.append(
                    Message(
                        body=body,
                        headers=getattr(props, "headers", None) or {},
                        delivery_tag=method.delivery_tag,
                    )
                )
            return out

        def ack(self, delivery_tag: int) -> None:
            self._ch.basic_ack(delivery_tag)

        def nack(self, delivery_tag: int, requeue: bool = False) -> None:
            self._ch.basic_nack(delivery_tag, requeue=requeue)

    return PikaBroker(uri)
