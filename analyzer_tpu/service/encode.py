"""Object graph <-> tensor codec for the service path.

The worker loads the reference's duck-typed match graphs (match -> rosters
-> participants -> player / participant_items) and rates them through the
vectorized scheduler + kernel, not the one-match object API. This module
packs a list of loaded match objects into a MatchStream + PlayerState and
scatters the HistoryOutputs back onto the objects with exactly the writes
``rater.py:140-169`` performs (quality, shared mu/sigma + delta snapshots,
mode mu/sigma, any_afk), preserving the gating rules for AFK/unsupported
matches (``rater.py:83-106``).
"""

from __future__ import annotations

from types import SimpleNamespace

import numpy as np
import jax.numpy as jnp

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core import constants
from analyzer_tpu.core.seeding import trueskill_seed_host
from analyzer_tpu.core.state import (
    COL_SEED_MU,
    COL_SEED_SIGMA,
    MAX_TEAM_SIZE,
    MU_LO,
    SIGMA_LO,
    TABLE_WIDTH,
    PlayerState,
)
from analyzer_tpu.sched.superstep import MatchStream


# Hoisted (col index, "<col>_mu", "<col>_sigma") triples: the encode loop
# reads 14 rating attributes per player, and building the attribute names
# with f-strings inside the loop cost ~40k string formats per 500-match
# batch on the consumer thread.
_RATING_ATTRS = tuple(
    (c, f"{col}_mu", f"{col}_sigma")
    for c, col in enumerate(constants.RATING_COLUMNS)
)


def row_bucket(n_players: int) -> int:
    """Power-of-two player-row bucket (floor 64) — the SINGLE owner of
    the service path's state-table sizing. ``EncodedBatch`` and
    ``Worker.warmup`` must agree on this, or warmup compiles shapes
    production never hits and the first real batch pays the XLA stall
    warmup exists to prevent."""
    return max(64, 1 << max(n_players - 1, 0).bit_length())


class PoisonError(Exception):
    """Base for encode failures attributable to SPECIFIC matches.

    ``api_ids`` names the offending match(es), so the worker can
    dead-letter exactly those messages and rate the rest — one corrupt
    record costs one message, not the whole 500-message batch. This
    dominates both the reference's whole-batch policy
    (``worker.py:110-120``) and round 2's strict divergence (which
    dead-lettered all 500). Unattributable failures (store errors,
    bugs) still fail the whole batch.
    """

    def __init__(self, api_ids, message):
        super().__init__(message)
        self.api_ids = tuple(api_ids)


class PoisonMatchError(PoisonError, ValueError):
    """A structurally malformed match (winner flags, team size)."""


class PoisonTierError(PoisonError, KeyError):
    """The reference's out-of-table skill-tier KeyError
    (``rater.py:60``), attributed to every ratable match that would
    consult the bad seed — a KeyError subclass so the reference's
    exception-type contract holds (tests/test_rater_parity.py)."""


class EncodedBatch:
    """A batch of match objects packed for the tensor path, with the maps
    needed to write results back.

    ``bucket_rows=True`` pads the player table to the next power-of-two
    row count (floor 64): the table shape is part of every compiled
    kernel's signature, so without bucketing each distinct
    distinct-player count would trigger a fresh XLA compile in the
    service loop (the worker's recompile guard, together with its pinned
    schedule width). Ghost rows are NaN-rated, never referenced by any
    match slot, and cost only bytes."""

    def __init__(self, matches, cfg: RatingConfig, bucket_rows: bool = False):
        self.matches = list(matches)
        self.cfg = cfg

        # Player rows: one per distinct player object (by api_id).
        self.row_of: dict[str, int] = {}
        self.player_at: list[object] = []
        for m in self.matches:
            for part in getattr(m, "participants", []):
                player = part.player[0]
                if player.api_id not in self.row_of:
                    self.row_of[player.api_id] = len(self.player_at)
                    self.player_at.append(player)
        p = len(self.player_at)
        self.n_players = p
        alloc = row_bucket(p) if bucket_rows else p

        # State table from object attributes (NaN for SQL NULL / None).
        table = np.full((alloc + 1, TABLE_WIDTH), np.nan, np.float32)
        rr = np.full((alloc + 1,), np.nan, np.float32)
        rb = np.full((alloc + 1,), np.nan, np.float32)
        ti = np.zeros((alloc + 1,), np.int32)
        bad_tier: dict[int, object] = {}  # row -> out-of-table tier value
        for r, player in enumerate(self.player_at):
            # __dict__.get is ~2x getattr, but it is only CORRECT where
            # the instance dict is the whole truth — exactly
            # SimpleNamespace (SqlStore's loaded graphs). Any other type
            # may serve attributes through properties, class defaults,
            # __getattr__ or ORM descriptors (which a bare __dict__ probe
            # would silently read as None = unrated), so everything else
            # keeps the duck-typed getattr path.
            if type(player) is SimpleNamespace:
                d = player.__dict__
                get, get_req = d.get, d.__getitem__
            else:
                def get(name, _p=player):
                    return getattr(_p, name, None)

                def get_req(name, _p=player):
                    return getattr(_p, name)
            for c, mu_col, sg_col in _RATING_ATTRS:
                mu = get(mu_col)
                if mu is not None:
                    table[r, MU_LO + c] = float(mu)
                    # get_req raises on a missing sigma (KeyError /
                    # AttributeError by path) — a mu without its sigma is
                    # malformed data, same contract as before.
                    table[r, SIGMA_LO + c] = float(get_req(sg_col))
            if player.rank_points_ranked is not None:
                rr[r] = float(player.rank_points_ranked)
            if player.rank_points_blitz is not None:
                rb[r] = float(player.rank_points_blitz)
            tier = player.skill_tier
            if tier is not None:
                if not (constants.MIN_SKILL_TIER <= tier <= constants.MAX_SKILL_TIER):
                    # The reference KeyErrors on out-of-table tiers, but
                    # only when get_trueskill_seed actually consults the
                    # table — i.e. the player has no shared rating and no
                    # nonzero rank points AND appears in a ratable match
                    # (rater.py:44-60,115-119). Record now, decide after
                    # the match tensors are built; meanwhile clamp like
                    # the tensor path so the (unused) baked seed is sane.
                    bad_tier[r] = tier
                    ti[r] = int(
                        min(max(tier, constants.MIN_SKILL_TIER), constants.MAX_SKILL_TIER)
                    )
                else:
                    ti[r] = int(tier)
        seed_mu, seed_sigma = trueskill_seed_host(rr, rb, ti, cfg)
        table[:, COL_SEED_MU] = seed_mu
        table[:, COL_SEED_SIGMA] = seed_sigma
        self.state = PlayerState(
            table=jnp.asarray(table),
            rank_points_ranked=jnp.asarray(rr),
            rank_points_blitz=jnp.asarray(rb),
            skill_tier=jnp.asarray(ti),
            seed_cfg=cfg,
        )

        # Match tensors. Structural problems are COLLECTED across the
        # whole batch and raised as ONE PoisonMatchError naming every
        # offender — a worker isolating them then retries once, not once
        # per bad match (which would re-load and re-encode the remaining
        # batch per incident, quadratic in the worst case).
        n = len(self.matches)
        idx = np.full((n, 2, MAX_TEAM_SIZE), -1, np.int32)
        winner = np.zeros((n,), np.int32)
        mode = np.full((n,), constants.UNSUPPORTED_MODE_ID, np.int32)
        afk = np.zeros((n,), bool)
        poison: dict[str, str] = {}  # api_id -> reason
        # slot -> participant object, for the per-participant write-back
        self.slot_part: list[list[list[object]]] = []
        for i, m in enumerate(self.matches):
            mode[i] = constants.MODE_TO_ID.get(m.game_mode, constants.UNSUPPORTED_MODE_ID)
            rosters = list(m.rosters)
            parts_grid: list[list[object]] = [[], []]
            bad = len(rosters) != 2
            if not bad:
                wins = [bool(r.winner) for r in rosters]
                if wins[0] == wins[1]:
                    poison[m.api_id] = (
                        f"rosters must have exactly one winner, got winner "
                        f"flags {wins}"
                    )
                    self.slot_part.append(parts_grid)
                    continue  # tensors stay inert; the raise below gates use
                winner[i] = 0 if wins[0] else 1
                oversize = False
                for t, roster in enumerate(rosters):
                    plist = list(roster.participants)
                    if len(plist) > MAX_TEAM_SIZE:
                        poison[m.api_id] = (
                            f"team of {len(plist)} exceeds max team size "
                            f"{MAX_TEAM_SIZE}"
                        )
                        oversize = True
                        break
                    for s, part in enumerate(plist):
                        idx[i, t, s] = self.row_of[part.player[0].api_id]
                    parts_grid[t] = plist
                if oversize:
                    idx[i] = -1
                    self.slot_part.append([[], []])
                    continue
            anyafk = bad or any(
                p.went_afk == 1 for p in getattr(m, "participants", [])
            )
            afk[i] = anyafk
            self.slot_part.append(parts_grid)
            # write_back needs participant_items[0] for every participant
            # of a supported-mode match (gate path: any_afk on
            # m.participants; rated path: mode mu/sigma on the slotted
            # ones — rater.py:96-106,163-169). The reference IndexErrors
            # here and dead-letters the whole batch; naming the match now
            # lets the worker isolate it instead.
            if m.api_id not in poison and mode[i] != constants.UNSUPPORTED_MODE_ID:
                for part in (
                    list(getattr(m, "participants", []))
                    + [p for t in parts_grid for p in t]
                ):
                    if not getattr(part, "participant_items", None):
                        poison[m.api_id] = (
                            f"participant {part.api_id!r} has no "
                            "participant_items row (write-back target, "
                            "rater.py:104,169)"
                        )
                        break
        if poison:
            raise PoisonMatchError(
                tuple(poison),
                "; ".join(f"match {k}: {v}" for k, v in poison.items()),
            )

        self.stream = MatchStream(
            player_idx=idx, winner=winner, mode_id=mode, afk=afk
        )

        if bad_tier:
            # Reference-faithful KeyError gating (rater.py:44-60,115-119):
            # an out-of-table tier only raises when the tier table would
            # actually be consulted — the player is in at least one RATABLE
            # match (AFK/unsupported matches return before seeding), has no
            # shared rating, and has no nonzero rank points (0/None are
            # "missing", the fallback-1 contract).
            ratable = (mode >= 0) & ~afk
            used = np.unique(idx[ratable])
            used = used[used >= 0]
            hit_any = np.zeros(n, bool)
            reasons: list[str] = []
            for r in used:
                r = int(r)
                if r not in bad_tier:
                    continue
                no_shared = np.isnan(table[r, MU_LO])
                no_points = (np.isnan(rr[r]) or rr[r] == 0) and (
                    np.isnan(rb[r]) or rb[r] == 0
                )
                if no_shared and no_points:
                    # Every ratable match with this player consults the
                    # same bad seed — isolating fewer would just fail
                    # again on the next retry; all offenders are
                    # collected into ONE raise for the same reason the
                    # structural pass above collects.
                    hit_any |= ratable & (idx == r).any(axis=(1, 2))
                    reasons.append(
                        f"player {self.player_at[r].api_id}: skill_tier "
                        f"{bad_tier[r]} outside [{constants.MIN_SKILL_TIER}, "
                        f"{constants.MAX_SKILL_TIER}] and the seed would be "
                        "consulted (no shared rating, no rank points)"
                    )
            if reasons:
                raise PoisonTierError(
                    tuple(self.matches[i].api_id for i in np.flatnonzero(hit_any)),
                    "; ".join(reasons),
                )

    def write_back(self, outs) -> None:
        """Applies HistoryOutputs (stream order) to the object graph with
        the reference's write set. Caller guarantees outs covers
        ``self.matches`` 1:1."""
        for i, m in enumerate(self.matches):
            mode_id = int(self.stream.mode_id[i])
            if mode_id == constants.UNSUPPORTED_MODE_ID:
                continue  # rater.py:83-85 — untouched
            col = constants.RATING_COLUMNS[mode_id + 1]
            if not outs.updated[i]:
                # AFK/invalid gate: quality=0, any_afk=True everywhere,
                # no rating writes (rater.py:102-106).
                m.trueskill_quality = 0
                for part in m.participants:
                    part.participant_items[0].any_afk = True
                continue
            m.trueskill_quality = float(outs.quality[i])
            for t in range(2):
                for s, part in enumerate(self.slot_part[i][t]):
                    player = part.player[0]
                    sh_mu = float(outs.shared_mu[i, t, s])
                    sh_sg = float(outs.shared_sigma[i, t, s])
                    part.trueskill_mu = sh_mu
                    part.trueskill_sigma = sh_sg
                    part.trueskill_delta = float(outs.delta[i, t, s])
                    player.trueskill_mu = sh_mu
                    player.trueskill_sigma = sh_sg
                    q_mu = float(outs.mode_mu[i, t, s])
                    q_sg = float(outs.mode_sigma[i, t, s])
                    setattr(player, f"{col}_mu", q_mu)
                    setattr(player, f"{col}_sigma", q_sg)
                    items = part.participant_items[0]
                    items.any_afk = False
                    setattr(items, f"{col}_mu", q_mu)
                    setattr(items, f"{col}_sigma", q_sg)
