"""Virtual clock and deterministic traffic shaping for the soak loop.

The soak's whole determinism story hangs on two facts owned here:

  * **VirtualClock** — every pacing decision (idle-timeout flushes,
    queue-depth sampling throttles, tick boundaries) reads a clock the
    driver ADVANCES explicitly, never the wall. The same seed + config
    therefore replays the identical event order on any machine at any
    speed; wall time only ever appears in the artifact's *measured*
    block (latencies, wall throughput), which is explicitly outside the
    bit-identical contract.
  * **TrafficShaper** — fractional rates (e.g. 7.5 matches/s at a 0.4 s
    tick) become integer per-tick event counts through an error-carrying
    accumulator, so the long-run rate is exact and the per-tick sequence
    is a pure function of (rate, tick_s) — no RNG, no rounding drift.

graftlint GL028 enforces the discipline package-wide: no ``random.*``,
no seedless ``np.random.default_rng()``, no wall-clock reads in
``analyzer_tpu/loadgen/`` decision paths.
"""

from __future__ import annotations


class VirtualClock:
    """A monotonic clock whose only mutator is :meth:`advance`.

    Hand :meth:`monotonic` to ``Worker(clock=)`` and anything else that
    wants a ``time.monotonic``-shaped callable; the driver advances it
    once per tick (and per drain iteration), so "one second elapsed" is
    a statement about the SIMULATED schedule, not about the host.
    """

    __slots__ = ("_now",)

    def __init__(self, start: float = 0.0) -> None:
        self._now = float(start)

    @property
    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"virtual time cannot rewind (dt={dt})")
        self._now += float(dt)
        return self._now

    def monotonic(self) -> float:
        """The ``time.monotonic``-shaped read (bound-method friendly)."""
        return self._now


class TrafficShaper:
    """Deterministic integer event counts per tick from a fractional
    rate.

    ``due()`` is called exactly once per tick: the accumulator gains
    ``rate * tick_s``, the integer part is emitted, the fraction carries
    — so e.g. 2.5 events/tick yields 2, 3, 2, 3, ... and the cumulative
    count after N ticks is always ``floor(N * rate * tick_s)`` ± 1.
    """

    __slots__ = ("rate_per_s", "tick_s", "_acc")

    def __init__(self, rate_per_s: float, tick_s: float) -> None:
        if rate_per_s < 0 or tick_s <= 0:
            raise ValueError(
                f"need rate >= 0 and tick > 0 (got {rate_per_s}, {tick_s})"
            )
        self.rate_per_s = float(rate_per_s)
        self.tick_s = float(tick_s)
        self._acc = 0.0

    def due(self) -> int:
        self._acc += self.rate_per_s * self.tick_s
        n = int(self._acc)
        self._acc -= n
        return n


#: Default serve-query mix for the soak's concurrent read workload:
#: point lookups dominate (the production shape), with a steady trickle
#: of winprob, leaderboard, and tier-histogram traffic.
DEFAULT_QUERY_MIX = (
    ("ratings", 0.50),
    ("winprob", 0.25),
    ("leaderboard", 0.15),
    ("tiers", 0.10),
)


def choose_kind(rng, mix=DEFAULT_QUERY_MIX) -> str:
    """One deterministic draw from the (kind, weight) mix using exactly
    one ``rng`` stream read."""
    total = sum(w for _, w in mix)
    x = rng.random() * total
    for kind, w in mix:
        x -= w
        if x < 0:
            return kind
    return mix[-1][0]
