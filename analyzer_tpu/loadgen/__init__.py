"""loadgen: the closed-loop matchmaking soak harness (ROADMAP item 3).

Every BENCH_*/SERVE_BENCH_* number is open-loop — fixed synthetic
batches into the runners, a canned query mix into the serve plane. This
package closes the loop into the production shape: a matchmaker samples
active players by an activity distribution, queues them by the
conservative rating the serve plane CURRENTLY publishes, balances teams
through the QueryEngine's winprob/quality path, resolves outcomes with a
TrueSkill-consistent win model, and publishes the finished matches onto
the ``analyze`` queue — while a concurrent-shaped query workload hits
``/v1/*``. Ratings drift therefore feeds back into matchmaking exactly
like production, and the :class:`~analyzer_tpu.loadgen.driver.SoakDriver`
runs broker -> worker -> commit -> view publish under that load with
per-tick SLO sampling and a ``SOAK_r*.json`` artifact that
``cli benchdiff --family soak`` gates.

Everything here is DETERMINISTIC per (seed, config): player sampling,
match formation, outcomes, and query traffic all draw from seeded
``np.random.default_rng`` streams, and pacing decisions run on a
virtual clock — so a short CPU soak is a tier-1 test, not just a rig
artifact. graftlint GL028 bans unseeded randomness and wall-clock reads
in this package's decision paths (the few legitimate wall clocks — the
measured-latency block, realtime pacing sleeps — carry line-scoped
disables with reasons).
"""

from analyzer_tpu.loadgen.driver import SoakConfig, SoakDriver
from analyzer_tpu.loadgen.matchmaker import (
    EngineServeClient,
    FormedMatch,
    HttpServeClient,
    Matchmaker,
)
from analyzer_tpu.loadgen.outcomes import OutcomeModel
from analyzer_tpu.loadgen.shaper import TrafficShaper, VirtualClock

__all__ = [
    "EngineServeClient",
    "FormedMatch",
    "HttpServeClient",
    "Matchmaker",
    "OutcomeModel",
    "SoakConfig",
    "SoakDriver",
    "TrafficShaper",
    "VirtualClock",
]
