"""SoakDriver: the closed-loop soak — matchmaker -> broker -> worker ->
commit -> view publish -> query traffic — under one virtual clock.

One tick of virtual time runs the whole production loop once:

  1. the **matchmaker** forms this tick's matches FROM THE SERVED
     RATINGS (queue by served conservative rating, winprob-balanced
     splits — ``matchmaker.py``), the **outcome model** resolves winners
     from latent truth, and the finished matches land in the store and
     on the ``analyze`` queue;
  2. the **worker** consumes (bounded polls per tick, so overload shows
     up as queue depth instead of silently stretching the tick), rates,
     commits, and publishes a new view version at each commit boundary;
  3. the **query workload** hits ``/v1/*`` (HTTP or in-process) with a
     deterministic kind mix, so the read plane serves while the write
     plane ingests;
  4. **SLO samples**: queue depth, view-version staleness, dead
     letters, retraces past warmup — all deterministic; wall-clock
     latencies and throughput land in the artifact's *measured* block.

Determinism contract (pinned by ``tests/test_loadgen.py``): the
artifact's ``deterministic`` block — matches formed, outcomes, query
digests, SLO counters, per-tick trajectory — is BIT-IDENTICAL for the
same (seed, config), because every decision reads a seeded RNG stream
or the virtual clock (graftlint GL028 enforces this package-wide).

The emitted ``SOAK_r*.json`` artifact is gated by
``cli benchdiff --family soak``: absolute SLOs (zero dead letters, flat
steady-state retraces, bounded view staleness, drained backlog) from
the deterministic block, throughput/p99 regressions against the
previous artifact (``obs/benchdiff.py``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import time

import numpy as np

from analyzer_tpu.config import RatingConfig, ServiceConfig
from analyzer_tpu.loadgen.matchmaker import (
    EngineServeClient,
    HttpServeClient,
    Matchmaker,
    player_id,
)
from analyzer_tpu.loadgen.outcomes import OutcomeModel
from analyzer_tpu.loadgen.shaper import (
    DEFAULT_QUERY_MIX,
    TrafficShaper,
    VirtualClock,
    choose_kind,
)
from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs import get_registry, install_jax_hooks
# THE shared SLO owner (obs/slo.py): the driver's verdict, the
# `cli benchdiff --family soak` gate, and the live watchdog all walk
# the same declarative objective table — none of the three can drift.
from analyzer_tpu.obs.slo import soak_violations
from analyzer_tpu.obs.tracectx import (
    enable_tracing,
    headers as trace_headers,
    mint as trace_mint,
    tracing_enabled,
)

logger = get_logger(__name__)

#: Fixed leaderboard depth for the query workload (one compiled top-k
#: bucket; the engine's warmup ladder covers it).
LEADERBOARD_K = 10

#: Ids in one ratings point-lookup of the query workload — fixed so the
#: serve gather bucket is one shape (the matchmaker's pages are separate,
#: matchmaker.RATINGS_PAGE).
QUERY_RATINGS_IDS = 8


@dataclasses.dataclass(frozen=True)
class SoakConfig:
    """One soak's full parameterization. ``duration_s`` is VIRTUAL time
    (ticks = duration_s / tick_s); wall time only matters in realtime
    mode. Defaults are a CPU smoke soak — seconds, tier-1 safe."""

    seed: int = 0
    duration_s: float = 8.0
    tick_s: float = 1.0
    qps: float = 24.0  # matches formed per virtual second
    query_qps: float = 10.0  # serve queries per virtual second
    n_players: int = 400
    batch_size: int = 64
    polls_per_tick: int = 4
    team5_frac: float = 0.3
    afk_rate: float = 0.0
    activity_concentration: float = 1.2
    warmup: bool = True  # precompile worker + serve + publish ladders
    use_http: bool = True  # query workload over /v1/* vs in-process
    # Route the HTTP query workload through the serve FRONT DOOR
    # (serve/frontdoor.py — the concurrent socket plane + native codec)
    # instead of the worker's RoutedHTTPServer plane. Same engine, same
    # response bytes (the codec is differential-pinned), so the
    # deterministic block is BIT-IDENTICAL to both the RoutedHTTPServer
    # run and the in-process run per (seed, config) — pinned by
    # tests/test_frontdoor.py. Implies use_http.
    serve_http: bool = False
    # > 1 serves through the sharded plane (ShardedViewPublisher +
    # ShardedQueryEngine, docs/serving.md "Sharded plane"). The
    # deterministic block is BIT-IDENTICAL across serve_shards values
    # for the same (seed, config-otherwise) — the sharded engine's
    # contract, pinned by tests/test_loadgen.py.
    serve_shards: int = 1
    realtime: bool = False  # pace ticks against the wall clock
    # Causal tracing (obs/tracectx.py): every published match carries a
    # TraceContext through the broker, the worker's batches tag their
    # spans, and the artifact gains a `trace` block with the stage
    # decomposition + dominant stage. The DETERMINISTIC block is
    # bit-identical with tracing on or off (ids are recorded, never
    # branched on) — pinned by tests/test_trace.py.
    trace: bool = False
    # > 1 runs the ingest edge through the PartitionedBroker: the
    # analyze queue splits into partitions by player-shard (row % S —
    # the serve plane's mesh layout invariant; the driver stamps
    # x-partition from each match's first team-A row), with
    # per-partition depth/dead-letter accounting. The deterministic
    # block is BIT-IDENTICAL to the single-queue run per (seed, config)
    # — the broker's seq-merged delivery contract, pinned by
    # tests/test_ingest.py.
    broker_partitions: int = 1
    # Priority lanes (live vs backfill) on the partitioned broker, with
    # the AdmissionController arbitrating backfill behind live traffic.
    # Lanes alone are also deterministic-block-invariant (live-only
    # traffic is never reordered).
    priority_lanes: bool = False
    # Backfill/replay traffic (requires priority_lanes): re-publishes
    # already-rated match ids on the backfill lane at this rate — the
    # zero-downtime re-rate workload's ingest shape (ROADMAP item 4).
    # Re-rating is idempotent per match; backfill rides OUTSIDE
    # matches_published so the drain SLO still means "live work done".
    backfill_qps: float = 0.0
    max_view_lag_ticks: int = 2  # SLO: served view staleness bound
    min_matches_per_sec: float | None = None  # SLO: absolute wall floor
    max_p99_ms: float | None = None  # SLO: absolute serve-latency bound
    # SLO: stages that must NOT dominate the critical path (benchdiff's
    # queue_wait check, wired to the trace block — requires trace=True).
    forbid_dominant_stages: tuple = ()
    # The live SLO plane (obs/history.py + obs/slo.py): history sampler
    # + watchdog riding the worker's poll loop on the VIRTUAL clock.
    # The deterministic block is BIT-IDENTICAL with the plane on or off
    # per (seed, config) — nothing in it branches into the rating path
    # (pinned by tests/test_slo_plane.py). Off = the AB knob.
    slo_plane: bool = True
    # Continuous shadow audit (obs/audit.py): a seeded-hash sample of
    # the soak's served queries replays through the bit-exact oracle
    # off the hot path; the artifact gains an `audit` block (outside
    # the deterministic block) and audit mismatches gate the soak
    # verdict zero-tolerance. Also deterministic-block-invariant.
    audit: bool = False
    audit_sample_denom: int = 4
    # Zero-downtime migration under live load (ROADMAP item 4, the
    # `cli soak --migrate` judge): a seeded synthetic history streams
    # through the backfill engine (analyzer_tpu/migrate) into a STAGING
    # view lineage while the soak's live plane keeps serving —
    # admission-arbitrated against the live backlog — and traffic cuts
    # over atomically AFTER the measured window. The deterministic
    # block is BIT-IDENTICAL with the migration on or off per (seed,
    # config): the backfill publishes only into the staging lineage,
    # its compile ladder warms in prepare() before the retrace base is
    # read, and the cutover happens after every deterministic value is
    # captured (pinned by tests/test_migrate.py).
    migrate: bool = False
    migrate_matches: int = 400
    # obsd on the soak's worker (None = no listener): lets a fleet
    # Collector (obs/federate.py) scrape the run — the deterministic
    # block is BIT-IDENTICAL with a scraper attached or absent (the
    # scrape path is read-only; pinned by tests/test_federate.py).
    obs_port: int | None = None
    # Rating-quality plane (obs/quality.py): the calibration ledger
    # scores every committed batch's PRE-update win probability against
    # the realized outcome; the artifact gains a `quality` block and
    # the calibration artifact check (obs/slo.py) gates the verdict
    # once the volume floor is met. Observer-only: the deterministic
    # block is BIT-IDENTICAL with the plane on or off (the AB knob,
    # `cli soak --no-quality`; pinned by tests/test_quality.py).
    quality: bool = True

    @property
    def n_ticks(self) -> int:
        return max(1, int(round(self.duration_s / self.tick_s)))


class SoakDriver:
    """Owns the rig (broker, store, worker + serve plane) and the loop.

    ``run()`` executes the configured soak and returns the artifact
    dict; ``close()`` tears the rig down (idempotent; ``run`` does NOT
    close, so a test can inspect the live worker afterwards).
    """

    def __init__(self, config: SoakConfig | None = None) -> None:
        from analyzer_tpu.io.synthetic import synthetic_players
        from analyzer_tpu.service.broker import InMemoryBroker
        from analyzer_tpu.service.store import InMemoryStore
        from analyzer_tpu.service.worker import Worker

        self.cfg = config or SoakConfig()
        cfg = self.cfg
        # Causal tracing is a process-wide flag; remember the prior state
        # so close() restores it (a traced soak inside a test session
        # must not leak tracing into the next test).
        self._trace_prev: bool | None = None
        if cfg.trace and not tracing_enabled():
            self._trace_prev = False
            enable_tracing(True)
        install_jax_hooks()  # retraces countable before the first compile
        self.vclock = VirtualClock()
        if cfg.broker_partitions > 1 or cfg.priority_lanes:
            from analyzer_tpu.service.broker import PartitionedBroker

            self.broker = PartitionedBroker(
                partitions=cfg.broker_partitions, lanes=cfg.priority_lanes,
            )
        else:
            self.broker = InMemoryBroker()
        if cfg.backfill_qps > 0 and not cfg.priority_lanes:
            raise ValueError(
                "backfill_qps needs priority_lanes=True — backfill "
                "traffic without a lane would contend with live matches "
                "head-on, which is exactly what lanes exist to prevent"
            )
        self.store = InMemoryStore()
        self.rating_config = RatingConfig()
        service_cfg = ServiceConfig(
            batch_size=cfg.batch_size, idle_timeout=0.0, pipeline=False,
        )
        # Sequential worker on the virtual clock: the pipelined engine's
        # writer thread would put commit ORDER on wall-time scheduling,
        # which the bit-identical contract cannot absorb.
        self.worker = Worker(
            self.broker, self.store, service_cfg, self.rating_config,
            clock=self.vclock.monotonic, pipeline=False, serve_port=0,
            serve_shards=cfg.serve_shards, obs_port=cfg.obs_port,
            slo_plane=cfg.slo_plane, audit=cfg.audit,
            audit_seed=cfg.seed, audit_sample_denom=cfg.audit_sample_denom,
            quality=cfg.quality,
        )
        self.players = synthetic_players(cfg.n_players, seed=cfg.seed)
        self.outcomes = OutcomeModel(
            self.players, self.rating_config, seed=cfg.seed
        )
        self.frontdoor = None
        if cfg.serve_http:
            from analyzer_tpu.serve.frontdoor import FrontDoor

            self.frontdoor = FrontDoor(self.worker.query_engine)
            self.client = HttpServeClient(self.frontdoor.url)
        elif cfg.use_http:
            self.client = HttpServeClient(self.worker.serve_server.url)
        else:
            self.client = EngineServeClient(self.worker.query_engine)
        self.matchmaker = Matchmaker(
            self.players, self.client, seed=cfg.seed,
            cfg=self.rating_config,
            activity_concentration=cfg.activity_concentration,
            team5_frac=cfg.team5_frac,
        )
        # Driver-level draws (afk flags, query kinds/payloads): a third
        # stream so query traffic never perturbs formation or outcomes.
        self.qrng = np.random.default_rng(
            np.random.SeedSequence(entropy=cfg.seed, spawn_key=(2,))
        )
        self._seq = 0
        self._backfill_cursor = 0
        self._backfill_published = 0
        self._player_cache: dict[int, object] = {}
        self._match_digest = hashlib.sha256()
        self._query_digest = hashlib.sha256()
        self._closed = False
        # Migration rig (cfg.migrate): filled by _prepare_migration.
        self._mig_data: bytes | None = None
        self._mig_state0 = None
        self._mig_reference = None  # the from-scratch re-rate's table
        self._mig_result: dict = {}
        self._mig_thread = None
        self._mig_lineage = None

    # -- rig preparation ---------------------------------------------------
    def prepare(self) -> None:
        """Primes the served view with the seeded population and (when
        ``cfg.warmup``) precompiles every shape the soak can hit — the
        production discipline (`Worker.warmup`, `QueryEngine.warmup`),
        which is also what makes "zero steady-state retraces" a gateable
        SLO instead of a race against the compile cache."""
        from analyzer_tpu.core.state import PlayerState

        cfg = self.cfg
        state = PlayerState.create(
            cfg.n_players,
            rank_points_ranked=self.players.rank_points_ranked,
            rank_points_blitz=self.players.rank_points_blitz,
            skill_tier=self.players.skill_tier,
            cfg=self.rating_config,
        )
        ids = [player_id(i) for i in range(cfg.n_players)]
        rows = np.asarray(state.table)[: cfg.n_players]
        # Version 1: every player known-but-unrated, seeds served — the
        # production bootstrap from the player table. Matchmaking reads
        # these seed estimates until real posteriors land.
        self.worker.view_publisher.publish_rows(ids, rows)
        if cfg.warmup:
            self.worker.warmup()
            self.worker.query_engine.warmup()
            self._warm_publish_buckets(ids, rows)
        if cfg.migrate:
            # Build the migration history AND run the backfill engine
            # once to completion on a throwaway staging lineage: this is
            # simultaneously the compile warmup for every shape the
            # concurrent run will hit (it runs BEFORE the retrace base
            # below, so the flat-steady-retraces SLO still means what it
            # says) and the from-scratch reference table the acceptance
            # check pins the migrated lineage against bit for bit.
            self._prepare_migration()
        self._retrace_base = float(
            get_registry().counter("jax.retraces_total").value
        )

    def _warm_publish_buckets(self, ids, rows) -> None:
        """Compiles the view publisher's patch-scatter ladder for every
        id-count bucket a commit can carry (the publisher's own
        ``warm_patch_buckets`` — re-publishing seed pages with
        idempotent content; versions advance, values do not). Without
        this the Nth distinct batch size would compile mid-soak and
        count against the retrace SLO. The ladder LENGTH is a pure
        function of the cap and the published population — identical
        across plane topologies, so the soak's version sequence (and
        therefore its deterministic block) does not depend on
        ``serve_shards``."""
        from analyzer_tpu.core.state import MAX_TEAM_SIZE

        self.worker.view_publisher.warm_patch_buckets(
            self.cfg.batch_size * 2 * MAX_TEAM_SIZE
        )

    # -- match materialization --------------------------------------------
    def _player_obj(self, row: int):
        """The SHARED duck-typed player object for ``row`` — one object
        per player for the whole soak, so the worker's write-back
        updates the priors the next batch loads (the store half of the
        closed loop)."""
        obj = self._player_cache.get(row)
        if obj is None:
            from analyzer_tpu.fixtures import fake_player

            p = self.players

            def _opt(x):
                return None if np.isnan(x) else float(x)

            obj = fake_player(
                skill_tier=int(p.skill_tier[row]),
                rank_points_ranked=_opt(p.rank_points_ranked[row]),
                rank_points_blitz=_opt(p.rank_points_blitz[row]),
            )
            obj.api_id = player_id(row)
            self._player_cache[row] = obj
        return obj

    def _build_match(self, formed, winner: int, afk: bool):
        from analyzer_tpu.fixtures import (
            fake_match,
            fake_participant,
            fake_roster,
        )

        rosters = []
        for t, rows in enumerate((formed.team_a_rows, formed.team_b_rows)):
            parts = [
                fake_participant(
                    player=self._player_obj(r),
                    skill_tier=int(self.players.skill_tier[r]),
                    went_afk=bool(afk and t == 0 and s == 0),
                )
                for s, r in enumerate(rows)
            ]
            rosters.append(
                fake_roster(winner=int(t == winner), participants=parts)
            )
        match = fake_match(formed.mode, rosters, api_id=f"soak-{self._seq:08d}")
        match.created_at = self._seq
        self._seq += 1
        return match

    def _publish_matches(self, n: int) -> int:
        """Forms, resolves, stores and enqueues ``n`` matches; folds
        each into the match digest. Returns the count published."""
        formed = self.matchmaker.form(n)
        reg = get_registry()
        for m in formed:
            winner, p_model = self.outcomes.resolve(
                m.team_a_rows, m.team_b_rows
            )
            afk = bool(self.qrng.random() < self.cfg.afk_rate)
            match = self._build_match(m, winner, afk)
            self.store.add_match(match)
            # The causal chain's first link: the TraceContext is minted
            # the moment the match enters the broker and rides the
            # message headers (None/no headers when tracing is off —
            # the digests below never see it either way).
            ctx = trace_mint(match.api_id)
            headers = dict(trace_headers(ctx) or {})
            if self.cfg.broker_partitions > 1:
                # Home-shard routing: the first team-A row's shard under
                # the mesh layout invariant (row % S — the same function
                # the serve plane routes lookups by). Header-routed so
                # the broker never has to parse match payloads.
                headers["x-partition"] = (
                    int(m.team_a_rows[0]) % self.cfg.broker_partitions
                )
            self.broker.publish(
                self.worker.config.queue, match.api_id.encode(),
                headers=headers or None,
            )
            self._match_digest.update(
                json.dumps(
                    {
                        "id": match.api_id,
                        "mode": m.mode,
                        "a": m.team_a_ids,
                        "b": m.team_b_ids,
                        "split": m.split,
                        "p_served": m.p_a,
                        "quality": m.quality,
                        "p_model": p_model,
                        "winner": winner,
                        "afk": afk,
                    },
                    sort_keys=True,
                ).encode()
            )
        reg.counter("soak.matches_published_total").add(len(formed))
        return len(formed)

    def _publish_backfill(self, n: int) -> int:
        """Re-publishes ``n`` already-stored match ids on the backfill
        lane (cycling oldest-first) — the replay/re-rate ingest shape.
        Deterministic: a pure cursor walk over the match sequence, no
        draws. No-op until live matches exist."""
        if self._seq == 0:
            return 0
        sent = 0
        for _ in range(n):
            mid = f"soak-{self._backfill_cursor % self._seq:08d}"
            self._backfill_cursor += 1
            self.broker.publish(
                self.worker.config.queue, mid.encode(),
                headers={"x-lane": "backfill"},
            )
            sent += 1
        self._backfill_published += sent
        return sent

    # -- zero-downtime migration under load (cfg.migrate) ------------------
    def _migration_state(self):
        """A fresh pre-migration player table — what a from-scratch
        season re-rate starts from (same construction as prepare())."""
        from analyzer_tpu.core.state import PlayerState

        return PlayerState.create(
            self.cfg.n_players,
            rank_points_ranked=self.players.rank_points_ranked,
            rank_points_blitz=self.players.rank_points_blitz,
            skill_tier=self.players.skill_tier,
            cfg=self.rating_config,
        )

    def _prepare_migration(self) -> None:
        """Synthesizes the seeded migration history, then runs the
        backfill engine once (throwaway staging lineage) — the compile
        warmup AND the bit-identity reference table."""
        import os
        import tempfile

        import numpy as np

        from analyzer_tpu.io.csv_codec import save_stream_csv
        from analyzer_tpu.io.synthetic import synthetic_stream
        from analyzer_tpu.migrate import rate_backfill
        from analyzer_tpu.serve import ViewPublisher

        cfg = self.cfg
        stream = synthetic_stream(
            cfg.migrate_matches, self.players, seed=cfg.seed + 7,
        )
        with tempfile.TemporaryDirectory() as tmp:
            path = os.path.join(tmp, "migration.csv")
            save_stream_csv(path, stream)
            with open(path, "rb") as f:
                self._mig_data = f.read()
        self._mig_state0 = self._migration_state()
        warm_staging = ViewPublisher()
        ref, _ = rate_backfill(
            self._mig_state0, self._mig_data, self.rating_config,
            staging=warm_staging,
        )
        self._mig_reference = np.asarray(ref.table)

    def _run_migration(self) -> None:
        """The concurrent backfill (its own thread, WALL time — it lives
        entirely outside the deterministic block): streams the history
        into the staging lineage under the admission controller, gated
        on the soak's live backlog."""
        import time as _time

        from analyzer_tpu.loadgen.matchmaker import player_id
        from analyzer_tpu.migrate import LineageManager, rate_backfill
        from analyzer_tpu.service.broker import AdmissionController

        queue = self.worker.config.queue

        def live_backlog() -> int:
            return self.broker.qsize(queue) + len(self.worker.queue)

        self._mig_lineage = LineageManager(self.worker.view_publisher)
        staging = self._mig_lineage.begin()
        stats: dict = {}
        t0 = _time.perf_counter()  # graftlint: disable=GL028 — measured-block wall anchor, not a decision input
        try:
            final, _ = rate_backfill(
                self._migration_state(), self._mig_data,
                self.rating_config,
                staging=staging,
                ids=[player_id(i) for i in range(self.cfg.n_players)],
                admission=AdmissionController(),
                live_backlog=live_backlog,
                stats_out=stats,
            )
        except BaseException as e:  # noqa: BLE001 — surfaced in the artifact
            self._mig_result.update(error=repr(e), stats=stats)
            self._mig_lineage.abort()
            return
        wall = _time.perf_counter() - t0  # graftlint: disable=GL028 — measured-block wall clock, not a decision input
        import numpy as np

        self._mig_result.update(
            table=np.asarray(final.table), stats=stats, wall_s=wall,
        )

    def _finish_migration(self) -> dict:
        """Joins the backfill, verifies the migrated lineage bit-for-bit
        against the from-scratch reference, and performs the atomic
        cutover. Called strictly AFTER the artifact's deterministic
        block is built — nothing here can perturb it. Returns the
        artifact's ``migration`` block (wall-derived, like `measured`)."""
        import numpy as np

        if self._mig_thread is not None:
            self._mig_thread.join(timeout=600)
        res = self._mig_result
        block: dict = {
            "ran": True,
            "matches": self.cfg.migrate_matches,
            "error": res.get("error"),
        }
        if "error" in res or "table" not in res:
            block["finished"] = False
            return block
        stats = res["stats"]
        pre_version = self.worker.view_publisher.version
        pre_cutover_view = self.worker.view_publisher.current()
        bit_identical = bool(
            np.array_equal(res["table"], self._mig_reference, equal_nan=True)
        )
        view = self._mig_lineage.cutover()
        served = np.asarray(view.table)
        cutover_identical = bool(
            np.array_equal(
                served[: view.n_players],
                res["table"][: view.n_players],
                equal_nan=True,
            )
        )
        wall = res["wall_s"]
        block.update(
            finished=True,
            streamed=bool(stats.get("streamed")),
            bit_identical=bit_identical,
            cutover_serves_migrated_table=cutover_identical,
            backfill_wall_s=round(wall, 3),
            backfill_matches_per_sec=(
                round(stats.get("matches", 0) / wall, 1) if wall > 0 else None
            ),
            ttfd_s=(
                round(stats["ttfd_s"], 4)
                if stats.get("ttfd_s") is not None else None
            ),
            supersteps=stats.get("n_steps"),
            occupancy=round(stats.get("occupancy", 0.0), 3),
            cutover_pause_ms=round(
                (self._mig_lineage.cutover_pause_s or 0.0) * 1e3, 3
            ),
            lineage_versions={
                "pre_cutover_live": pre_version,
                "post_cutover_live": view.version,
            },
        )
        if self.cfg.quality:
            try:
                block["quality"] = self._migration_quality(
                    res["table"], pre_cutover_view
                )
            except Exception as e:  # noqa: BLE001 — advisory evidence only
                block["quality"] = {"error": repr(e)}
        return block

    def _migration_quality(self, migrated_table, live_view) -> dict | None:
        """The staging-vs-live replay judge (obs/quality.py
        :func:`score_table`): both lineages score the IDENTICAL
        migration window with the identical serve-plane link — did the
        backfill produce a better-fitting table than the live lineage
        it replaces? Advisory evidence (never gates the verdict: the
        live lineage never saw this window, so a fit gap is expected —
        the signal is a *migrated* table that fits WORSE)."""
        import io as _io

        import numpy as np

        from analyzer_tpu.io.csv_codec import load_stream_csv
        from analyzer_tpu.obs.quality import score_table

        if live_view is None:
            return None
        stream = load_stream_csv(_io.StringIO(self._mig_data.decode()))
        keys = ("matches_scored", "brier", "logloss", "ece")
        migrated = score_table(migrated_table, stream, self.rating_config)
        live = score_table(
            np.asarray(live_view.host_table()), stream, self.rating_config
        )
        return {
            "replay_matches": self.cfg.migrate_matches,
            "migrated": {k: migrated[k] for k in keys},
            "live_pre_cutover": {k: live[k] for k in keys},
        }

    # -- query workload ----------------------------------------------------
    def _issue_queries(self, n: int, latencies_ms: list,
                       counts: dict) -> None:
        """``n`` serve queries with the deterministic kind mix. Payload
        draws come off the driver stream; latency is the one legitimate
        wall read (measured block, never a decision input)."""
        client = self.client
        for _ in range(n):
            kind = choose_kind(self.qrng, DEFAULT_QUERY_MIX)
            if kind == "ratings":
                rows = self.matchmaker.sample_rows(
                    QUERY_RATINGS_IDS, rng=self.qrng
                )
                call = (client.get_ratings, ([player_id(r) for r in rows],))
            elif kind == "winprob":
                rows = self.matchmaker.sample_rows(6, rng=self.qrng)
                call = (
                    client.win_probability,
                    (
                        [player_id(r) for r in rows[:3]],
                        [player_id(r) for r in rows[3:]],
                    ),
                )
            elif kind == "leaderboard":
                call = (client.leaderboard, (LEADERBOARD_K,))
            else:
                call = (client.tiers, ())
            t0 = time.perf_counter()  # graftlint: disable=GL028 — measured-block latency, not a decision input
            resp = call[0](*call[1])
            dt = time.perf_counter() - t0  # graftlint: disable=GL028 — measured-block latency, not a decision input
            latencies_ms.append(dt * 1e3)
            counts[kind] = counts.get(kind, 0) + 1
            self._query_digest.update(
                (kind + "\n" + json.dumps(resp, sort_keys=True)).encode()
            )
        get_registry().counter("soak.queries_sent_total").add(n)

    # -- the loop ----------------------------------------------------------
    def run(self) -> dict:
        """Executes the soak and returns the SOAK artifact dict."""
        cfg = self.cfg
        reg = get_registry()
        reg.gauge("soak.qps_target").set(cfg.qps)
        self.prepare()
        if cfg.migrate:
            # The backfill runs CONCURRENTLY with the whole soak on its
            # own (wall-clock) thread, publishing only into the staging
            # lineage — live serving, the digests, and every counter in
            # the deterministic block are untouched until the cutover,
            # which happens after that block is captured.
            import threading

            self._mig_thread = threading.Thread(
                target=self._run_migration, name="soak-migrate", daemon=True
            )
            self._mig_thread.start()
        match_shaper = TrafficShaper(cfg.qps, cfg.tick_s)
        query_shaper = TrafficShaper(cfg.query_qps, cfg.tick_s)
        backfill_shaper = (
            TrafficShaper(cfg.backfill_qps, cfg.tick_s)
            if cfg.backfill_qps > 0 else None
        )
        published = 0
        query_counts: dict[str, int] = {}
        latencies_ms: list[float] = []
        trajectory: list[list] = []
        depth_max = 0
        lag_ticks = 0
        lag_ticks_max = 0
        last_version = self.worker.view_publisher.version
        wall_t0 = time.perf_counter()  # graftlint: disable=GL028 — measured-block wall anchor, not a decision input
        queue = self.worker.config.queue

        def sample(tick: int) -> int:
            nonlocal depth_max, lag_ticks, lag_ticks_max, last_version
            depth = self.broker.qsize(queue) + len(self.worker.queue)
            depth_max = max(depth_max, depth)
            version = self.worker.view_publisher.version
            rated = self.worker.matches_rated
            # Staleness in ticks: a tick with work still pending and no
            # new published version ages the view; a publish (or a fully
            # drained loop) resets it. Deterministic — purely counters.
            # (>=: backfill re-rates push rated past published — a fully
            # drained loop is still "fresh"; == and >= agree otherwise.)
            if version != last_version or (depth == 0 and rated >= published):
                lag_ticks = 0
            else:
                lag_ticks += 1
            lag_ticks_max = max(lag_ticks_max, lag_ticks)
            last_version = version
            trajectory.append([tick, depth, version, rated])
            return depth

        for tick in range(cfg.n_ticks):
            self.vclock.advance(cfg.tick_s)
            # Arrivals are PACED across the tick's poll slots instead of
            # burst-published at the tick edge: a tick is the virtual
            # clock's granularity, not a claim that a second's worth of
            # matches lands in one instant — and a burst would charge
            # the whole backlog's wall time to `queue_wait`, swamping
            # the stage decomposition with a driver artifact. Slot
            # sizing is a pure function of (due, polls_per_tick):
            # deterministic, leftovers land on the earliest slots.
            due = match_shaper.due()
            backfill_due = (
                backfill_shaper.due() if backfill_shaper is not None else 0
            )
            polls = max(1, cfg.polls_per_tick)
            for p in range(polls):
                share = due // polls + (1 if p < due % polls else 0)
                if share:
                    published += self._publish_matches(share)
                bf_share = backfill_due // polls + (
                    1 if p < backfill_due % polls else 0
                )
                if bf_share:
                    self._publish_backfill(bf_share)
                self.worker.poll()
            self._issue_queries(query_shaper.due(), latencies_ms, query_counts)
            sample(tick)
            reg.counter("soak.ticks_total").add(1)
            reg.gauge("soak.virtual_seconds").set(self.vclock.now)
            if cfg.realtime:
                target = wall_t0 + (tick + 1) * cfg.tick_s
                delay = target - time.perf_counter()  # graftlint: disable=GL028 — realtime pacing reads the wall by definition
                if delay > 0:
                    time.sleep(delay)  # graftlint: disable=GL028 — realtime pacing sleep, virtual schedule already fixed

        # Drain: the backlog must clear in bounded virtual time — an
        # undrainable soak is itself an SLO violation, not a hang.
        drained = False
        for extra in range(cfg.n_ticks + 100):
            if (
                self.broker.qsize(queue) == 0
                and not self.worker.queue
                and self.worker.matches_rated >= published
            ):
                drained = True
                break
            self.vclock.advance(cfg.tick_s)
            for _ in range(cfg.polls_per_tick):
                self.worker.poll()
            sample(cfg.n_ticks + extra)
        # Flush the shadow-audit backlog: every sampled query must be
        # oracle-replayed before the artifact reads the mismatch count
        # (worker.drain also covers this on the production exit path).
        if self.worker.auditor is not None:
            self.worker.auditor.drain()
        wall_s = time.perf_counter() - wall_t0  # graftlint: disable=GL028 — measured-block wall clock, not a decision input

        retraces_steady = (
            float(reg.counter("jax.retraces_total").value)
            - self._retrace_base
        )
        # Causal-trace decomposition (obs/traceview.py): the same
        # per-stage breakdown `cli trace` renders, aggregated over the
        # soak's batches, so an SLO violation names the dominant stage.
        # Wall-time derived — it lives OUTSIDE the deterministic block.
        trace_block = None
        if tracing_enabled():
            from analyzer_tpu.obs import get_tracer
            from analyzer_tpu.obs.traceview import build_model, critical_path

            trace_block = critical_path(build_model(get_tracer().events()))
        lat = np.asarray(latencies_ms, np.float64)
        latency_ms = {
            "p50": round(float(np.percentile(lat, 50)), 3) if lat.size else None,
            "p90": round(float(np.percentile(lat, 90)), 3) if lat.size else None,
            "p99": round(float(np.percentile(lat, 99)), 3) if lat.size else None,
        }
        rated = self.worker.matches_rated
        artifact = {
            "metric": "soak.matches_per_sec",
            "value": round(rated / wall_s, 2) if wall_s > 0 else 0.0,
            "config": dataclasses.asdict(self.cfg),
            "deterministic": {
                "seed": self.cfg.seed,
                "ticks": cfg.n_ticks,
                "virtual_s": round(cfg.n_ticks * cfg.tick_s, 6),
                "matches_published": published,
                "matches_rated": rated,
                "matches_digest": self._match_digest.hexdigest(),
                "queries_digest": self._query_digest.hexdigest(),
                "queries": dict(sorted(query_counts.items())),
                "serve_calls": dict(sorted(self.client.calls.items())),
                "batches_ok": self.worker.batches_ok,
                "dead_letters": self.worker.dead_letters,
                "view_version_final": self.worker.view_publisher.version,
                "view_lag_ticks_max": lag_ticks_max,
                "queue_depth_max": depth_max,
                "queue_depth_final": (
                    self.broker.qsize(queue) + len(self.worker.queue)
                ),
                "retraces_steady": retraces_steady,
                "drained": drained,
                "backfill_published": self._backfill_published,
                "trajectory": trajectory,
            },
            "slo": {
                "pass": True,
                "violations": [],
                "thresholds": {
                    "max_view_lag_ticks": cfg.max_view_lag_ticks,
                    "min_matches_per_sec": cfg.min_matches_per_sec,
                    "max_p99_ms": cfg.max_p99_ms,
                    "forbid_dominant_stages": list(
                        cfg.forbid_dominant_stages
                    ) or None,
                },
            },
            "latency_ms": latency_ms,
            "measured": {
                "wall_s": round(wall_s, 3),
                "queries_per_sec": (
                    round(len(latencies_ms) / wall_s, 2) if wall_s > 0 else 0.0
                ),
            },
            "capture": {"degraded": False},
        }
        if self.frontdoor is not None:
            # Codec route accounting for the socket plane (OUTSIDE the
            # deterministic block — native vs fallback changes nothing
            # the digests see, by the codec's byte-parity contract).
            artifact["frontdoor"] = self.frontdoor.codec_stats()
        if trace_block is not None:
            artifact["trace"] = trace_block
            artifact["slo"]["dominant_stage"] = trace_block["dominant_stage"]
        if self.worker.auditor is not None:
            # The shadow audit's evidence (OUTSIDE the deterministic
            # block — offered counts include engine-internal retries):
            # sampled/checked/mismatch counters plus the first bounded
            # mismatch records. soak_violations gates mismatches == 0.
            artifact["audit"] = self.worker.auditor.stats()
            if self.worker.auditor.mismatches:
                artifact["audit"]["examples"] = [
                    {k: m[k] for k in ("kind", "key", "version")}
                    for m in self.worker.auditor.mismatches[:8]
                ]
        if self.worker.quality is not None:
            # The calibration ledger's evidence (obs/quality.py):
            # OUTSIDE the deterministic block — but itself
            # deterministic per (seed, config), byte-identical across
            # reruns (pinned by tests/test_quality.py). Attached
            # BEFORE soak_violations so the calibration artifact
            # check (obs/slo.py) judges this run's own reliability.
            artifact["quality"] = self.worker.quality.summary()
        if cfg.migrate:
            # Deterministic block is captured above; the cutover (and
            # its version bump) happens only now. The migration's own
            # acceptance — finished, streamed (no silent fall-back to
            # the offline re-rate), bit-identical to the from-scratch
            # reference — gates the soak verdict like any SLO.
            artifact["migration"] = self._finish_migration()
        violations = soak_violations(artifact)
        mig = artifact.get("migration")
        if mig is not None:
            if not mig.get("finished"):
                violations.append(
                    "migration: backfill did not finish "
                    f"({mig.get('error') or 'timed out'})"
                )
            else:
                if not mig.get("streamed"):
                    violations.append(
                        "migration: engine fell back to the offline "
                        "(non-streamed) re-rate path"
                    )
                if not mig.get("bit_identical"):
                    violations.append(
                        "migration: migrated lineage is NOT bit-identical "
                        "to the from-scratch re-rate"
                    )
                if not mig.get("cutover_serves_migrated_table"):
                    violations.append(
                        "migration: post-cutover live view does not serve "
                        "the migrated table"
                    )
        artifact["slo"]["violations"] = violations
        artifact["slo"]["pass"] = not violations
        if violations:
            reg.counter("soak.slo_violations_total").add(len(violations))
            logger.warning("soak SLO violations: %s", "; ".join(violations))
            if trace_block is not None and trace_block["dominant_stage"]:
                logger.warning(
                    "dominant stage over the soak's batches: %s "
                    "(artifact `trace` block has the full decomposition)",
                    trace_block["dominant_stage"],
                )
            # When a device profile was captured during the soak (dead
            # letter / degradation / SIGUSR2), attribute it right here:
            # the violation log then names the dominant device kernel
            # and the busy/idle split next to the dominant host stage.
            from analyzer_tpu.obs.prof import get_device_profiler

            last_capture = get_device_profiler().last_capture
            if last_capture is not None:
                from analyzer_tpu.obs.profview import analyze_capture

                att = analyze_capture(last_capture)
                if att["parsed"]:
                    dev_split = att["device"]
                    logger.warning(
                        "device profile %s: dominant kernel %s, busy "
                        "%.3f ms / idle %.3f ms (idle %.1f%% of the "
                        "capture window)",
                        last_capture, att["dominant_kernel"],
                        dev_split["busy_us"] / 1e3,
                        dev_split["idle_us"] / 1e3,
                        100 * dev_split["idle_frac"],
                    )
                else:
                    logger.warning(
                        "device profile %s did not parse: %s",
                        last_capture, att.get("error"),
                    )
        logger.info(
            "soak done: %d matches over %d ticks (%.1f wall s), slo=%s",
            rated, cfg.n_ticks, wall_s,
            "pass" if not violations else "FAIL",
        )
        return artifact

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            if self.frontdoor is not None:
                self.frontdoor.close()
            self.worker.close()
            if self._trace_prev is not None:
                enable_tracing(self._trace_prev)


def write_artifact(artifact: dict, path: str) -> None:
    """One pretty-printed SOAK artifact (the ``SOAK_rNN.json`` shape
    ``cli benchdiff --family soak`` scans for)."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=1, sort_keys=True)
        f.write("\n")
