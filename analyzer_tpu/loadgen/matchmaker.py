"""Matchmaking from the SERVED ratings: the soak loop's closed half.

The matchmaker never peeks at the rating table, the store, or the
latent skills — every number it decides on comes back through the same
read plane production matchmaking would use:

  * **queue ordering** — candidates sampled by the activity distribution
    (reusing :class:`analyzer_tpu.io.synthetic.AliasSampler`) are ranked
    by the *conservative* rating (``mu - 3*sigma``) the current
    published view serves; unrated players fall back to their served
    seed estimate, exactly like a ladder seeding fresh accounts.
  * **team balance** — candidate splits of the ranked queue are scored
    through the QueryEngine's winprob/quality path and the
    highest-quality split wins, so as ratings drift the matchmaker's
    pairings drift with them — the feedback loop the soak exists to
    exercise.

Requests ride a :class:`ServeClient`: in-process against a
:class:`~analyzer_tpu.serve.engine.QueryEngine`, or HTTP against a live
``/v1/*`` endpoint — both shapes are exercised in tier-1. Ratings
lookups go out in FIXED-SIZE pages (padded by repeating ids) so the
serve plane's gather-bucket ladder sees one shape and a warmed soak
stays retrace-free.

Determinism: one seeded generator, a fixed draw discipline (sampler
draws + the mode draw are the only consumers), and stable sorts keyed
(score, id).
"""

from __future__ import annotations

import dataclasses
import json
import urllib.parse

import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.obs.httpd import PooledHTTPClient
from analyzer_tpu.io.synthetic import AliasSampler, SyntheticPlayers

#: Fixed ratings-lookup page: every conservative-rating fetch pads to
#: this many ids so the serve gather ladder compiles exactly one shape.
RATINGS_PAGE = 64

#: 3v3 / 5v5 ratable modes the soak publishes (constants.MODES names).
MODE_3V3 = "ranked"
MODE_5V5 = "5v5_ranked"


@dataclasses.dataclass(frozen=True)
class FormedMatch:
    """One matchmade pairing, pre-outcome. Rows index the synthetic
    population; ids are the api ids the store/serve plane use."""

    mode: str
    team_a_rows: tuple[int, ...]
    team_b_rows: tuple[int, ...]
    team_a_ids: tuple[str, ...]
    team_b_ids: tuple[str, ...]
    p_a: float  # the SERVED winprob estimate for the chosen split
    quality: float  # the served match quality for the chosen split
    split: str  # which candidate split won ("snake" / "pairs")


class EngineServeClient:
    """ServeClient over an in-process QueryEngine (threaded or inline).
    Counts requests per kind so the driver can fold matchmaker traffic
    into the soak's served-query accounting."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self.calls: dict[str, int] = {}

    def _count(self, kind: str) -> None:
        self.calls[kind] = self.calls.get(kind, 0) + 1

    def get_ratings(self, ids) -> dict:
        self._count("ratings")
        return self.engine.get_ratings(ids)

    def win_probability(self, team_a, team_b) -> dict:
        self._count("winprob")
        return self.engine.win_probability(team_a, team_b)

    def leaderboard(self, k: int) -> dict:
        self._count("leaderboard")
        return self.engine.leaderboard(k)

    def tiers(self) -> dict:
        self._count("tiers")
        return self.engine.tier_histogram()


class HttpServeClient:
    """ServeClient over a live ``/v1/*`` endpoint (an HTTP *client* —
    the listening sockets stay in obs/ + serve/, graftlint GL024).
    Rides one pooled keep-alive connection
    (:class:`~analyzer_tpu.obs.httpd.PooledHTTPClient`): the soak's
    closed-loop query thread stops paying a TCP handshake per query,
    which is what lets ``--serve-http`` drive the frontdoor at socket
    rates instead of measuring connect latency."""

    def __init__(self, base_url: str, timeout: float = 30.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.calls: dict[str, int] = {}
        self.pool = PooledHTTPClient(self.base_url, timeout_s=timeout)

    def _get(self, kind: str, path: str, params: dict | None = None) -> dict:
        self.calls[kind] = self.calls.get(kind, 0) + 1
        if params:
            path += "?" + urllib.parse.urlencode(params)
        return json.loads(self.pool.get(path).decode("utf-8"))

    def get_ratings(self, ids) -> dict:
        return self._get("ratings", "/v1/ratings", {"ids": ",".join(ids)})

    def win_probability(self, team_a, team_b) -> dict:
        return self._get(
            "winprob", "/v1/winprob",
            {"a": ",".join(team_a), "b": ",".join(team_b)},
        )

    def leaderboard(self, k: int) -> dict:
        return self._get("leaderboard", "/v1/leaderboard", {"k": str(k)})

    def tiers(self) -> dict:
        return self._get("tiers", "/v1/tiers")


def player_id(row: int) -> str:
    """The soak population's api-id scheme (store + serve + artifact)."""
    return f"p{row:06d}"


def _snake_split(order: list) -> tuple[list, list]:
    """1st,4th,5th,8th,... vs 2nd,3rd,6th,7th,... — the classic draft
    that balances a strictly ranked queue."""
    a, b = [], []
    for i, x in enumerate(order):
        (a if i % 4 in (0, 3) else b).append(x)
    return a, b


def _pairs_split(order: list) -> tuple[list, list]:
    """Even vs odd ranks — the adjacent-pairs alternative."""
    return order[0::2], order[1::2]


class Matchmaker:
    """Forms ratable two-team matches from the served ratings.

    ``client`` is a ServeClient; ``seed`` fixes the formation stream
    (candidate draws + mode draws). Activity weights are the same
    Zipf shape :func:`analyzer_tpu.io.synthetic.synthetic_stream` uses,
    shuffled by this seed so "who is a grinder" varies per soak.
    """

    def __init__(
        self,
        players: SyntheticPlayers,
        client,
        seed: int = 0,
        cfg: RatingConfig | None = None,
        activity_concentration: float = 1.2,
        team5_frac: float = 0.3,
        ratings_page: int = RATINGS_PAGE,
    ) -> None:
        p = players.n_players
        if p < 2 * 5:
            raise ValueError(f"need at least 10 players to matchmake, got {p}")
        self.players = players
        self.client = client
        self.cfg = cfg or RatingConfig()
        self.team5_frac = float(team5_frac)
        self.ratings_page = int(ratings_page)
        self.rng = np.random.default_rng(
            np.random.SeedSequence(entropy=seed, spawn_key=(0,))
        )
        ranks = np.arange(1, p + 1, dtype=np.float64)
        weights = 1.0 / ranks**activity_concentration
        self.rng.shuffle(weights)
        self.sampler = AliasSampler(weights / weights.sum())
        # Fresh accounts the view has never seen rank at the seedless
        # floor — deterministic, and strictly below any served seed.
        self._fallback_conservative = float(
            self.cfg.mu0 - 3.0 * self.cfg.sigma0
        )

    # -- candidate sampling ----------------------------------------------
    def sample_rows(self, k: int, rng=None) -> list[int]:
        """``k`` DISTINCT player rows by activity weight, in draw order
        (the redraw loop preserves first-draw precedence). ``rng``
        defaults to the formation stream; the driver's query workload
        passes its own stream so read traffic never perturbs
        formation draws."""
        rng = self.rng if rng is None else rng
        out: dict[int, None] = {}
        while len(out) < k:
            for c in self.sampler.draw(rng, (k,)).tolist():
                if len(out) == k:
                    break
                out.setdefault(int(c), None)
        return list(out)

    # -- served-rating lookups -------------------------------------------
    def conservative_of(self, ids: list[str]) -> dict[str, float]:
        """Served conservative rating per id, via fixed-size ratings
        pages (padding repeats ids — lookups are idempotent). Unrated
        players use their served seed estimate; ids the view has never
        published fall back to the seedless floor."""
        out: dict[str, float] = {}
        uniq = list(dict.fromkeys(ids))
        page = self.ratings_page
        for lo in range(0, len(uniq), page):
            chunk = uniq[lo : lo + page]
            padded = chunk + [chunk[0]] * (page - len(chunk))
            resp = self.client.get_ratings(padded)
            for r in resp["ratings"]:
                if r["id"] in out:
                    continue
                if r["rated"]:
                    out[r["id"]] = float(r["conservative"])
                else:
                    out[r["id"]] = float(
                        r["seed_mu"] - 3.0 * r["seed_sigma"]
                    )
            for pid in resp.get("unknown", ()):
                out.setdefault(pid, self._fallback_conservative)
        return out

    # -- formation ---------------------------------------------------------
    def form(self, n: int) -> list[FormedMatch]:
        """Forms ``n`` matches. One conservative-rating sweep covers the
        whole call's candidates; each match then scores its candidate
        splits through the served winprob path and keeps the
        highest-quality one (ties: first candidate wins — "snake")."""
        if n <= 0:
            return []
        plans = []
        for _ in range(n):
            five = self.rng.random() < self.team5_frac
            mode, t = (MODE_5V5, 5) if five else (MODE_3V3, 3)
            rows = self.sample_rows(2 * t)
            plans.append((mode, rows))
        all_ids = [player_id(r) for _, rows in plans for r in rows]
        score = self.conservative_of(all_ids)
        out = []
        for mode, rows in plans:
            # Rank the queue best-first; ties break on the id so the
            # order is total and machine-independent.
            order = sorted(
                rows, key=lambda r: (-score[player_id(r)], player_id(r))
            )
            best = None
            for name, split in (
                ("snake", _snake_split(order)),
                ("pairs", _pairs_split(order)),
            ):
                a_ids = tuple(player_id(r) for r in split[0])
                b_ids = tuple(player_id(r) for r in split[1])
                resp = self.client.win_probability(a_ids, b_ids)
                cand = FormedMatch(
                    mode=mode,
                    team_a_rows=tuple(split[0]),
                    team_b_rows=tuple(split[1]),
                    team_a_ids=a_ids,
                    team_b_ids=b_ids,
                    p_a=float(resp["p_a"]),
                    quality=float(resp["quality"]),
                    split=name,
                )
                if best is None or cand.quality > best.quality:
                    best = cand
            out.append(best)
        return out
