"""TrueSkill-consistent outcome resolution for soak-formed matches.

The matchmaker forms teams from the SERVED ratings; the outcome model
resolves them from the population's LATENT skills — the ground truth the
rating system is trying to estimate. The win model is exactly the
TrueSkill likelihood with the latent skills as zero-variance means:

    P(team A wins) = Phi((sum mu_A - sum mu_B) / (beta * sqrt(n)))

i.e. the ``c`` of :mod:`analyzer_tpu.ops.trueskill` with every
``sigma_i = 0`` and no tau inflation — so the rating system's own
winprob estimates converge toward this model's probabilities as sigma
shrinks, which is what makes the closed loop a *calibration* testbed
and not just a load pattern.

Determinism: one seeded ``np.random.default_rng`` stream, exactly one
``random()`` read per resolved match.
"""

from __future__ import annotations

import math

import numpy as np

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.io.synthetic import SyntheticPlayers


class OutcomeModel:
    """Samples winners from the latent-skill gap through the TrueSkill
    link. ``resolve`` consumes exactly one RNG read per match, so the
    outcome sequence is a pure function of (seed, match sequence)."""

    def __init__(
        self,
        players: SyntheticPlayers,
        cfg: RatingConfig | None = None,
        seed: int = 0,
    ) -> None:
        self.players = players
        self.cfg = cfg or RatingConfig()
        # Distinct stream from the matchmaker's (same seed, different
        # spawn key): outcomes must not perturb formation draws.
        self.rng = np.random.default_rng(
            np.random.SeedSequence(entropy=seed, spawn_key=(1,))
        )

    def win_probability(self, team_a_rows, team_b_rows) -> float:
        """P(team A wins) from latent truth — the Phi link above."""
        skill = self.players.latent_skill
        gap = float(skill[list(team_a_rows)].sum()) - float(
            skill[list(team_b_rows)].sum()
        )
        n = len(team_a_rows) + len(team_b_rows)
        c = self.cfg.beta * math.sqrt(max(n, 1))
        t = gap / c
        return 0.5 * math.erfc(-t / math.sqrt(2.0))

    def resolve(self, team_a_rows, team_b_rows) -> tuple[int, float]:
        """(winner, p_a): winner is 0 when team A won. One RNG read."""
        p_a = self.win_probability(team_a_rows, team_b_rows)
        winner = 0 if self.rng.random() < p_a else 1
        return winner, p_a
