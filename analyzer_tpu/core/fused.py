"""Fused multi-superstep rating kernel: the VMEM-resident row chain.

:mod:`analyzer_tpu.core.update` established the per-superstep cost split
on v5e: gather + all closed-form compute ~35 us, the whole-row scatter
~370 us at B=512 — and BASELINE.md's "Scatter floor" study showed no
isolated scatter variant beats the ~72 ns/row serialization. The
remaining headroom is therefore not a better scatter but FEWER scatters:
this module executes a *window* of K conflict-free supersteps per
dispatch against a working set of the window's touched player rows —

  1. ONE gather pulls every touched row from the HBM table into the
     working set (``table[slot_rows]``, [n_slots, 16]);
  2. the K supersteps run entirely against the working set: each step
     gathers its batch rows by *slot* index, applies the unchanged
     closed-form TrueSkill update (:func:`~analyzer_tpu.core.update.
     rate_gathered` — the same traced ops as the reference kernel), and
     commits the posteriors back into the working set;
  3. ONE scatter writes the working set back to HBM.

A row that appears in ``r`` steps of the window pays the scatter floor
once instead of ``r`` times — and active players recur constantly (the
whole reason the scheduler needs conflict-free supersteps). The host
side already knows every window's touched rows, so the residency plan
(row -> slot map, :mod:`analyzer_tpu.sched.residency`) is computed
alongside schedule packing and shipped with the slab; the device never
sees player row ids inside the window, only slot ids.

Backends (``backend=`` on every entry point):

  * ``"scan"`` — a fused ``lax.scan`` body over the working set. The
    portable default: bit-identical semantics on every JAX backend, and
    already removes the per-step HBM round trip (XLA keeps the small
    carry hot; the scatter serialization now runs against an
    [n_slots, 16] buffer instead of the [P+1, 16] table).
  * ``"pallas"`` — the Pallas TPU kernel: the working set lives in a
    VMEM scratch buffer that persists across the sequential grid (one
    grid step per superstep), so the whole chain runs on-chip and HBM
    sees exactly one gather and one writeback per window.
  * ``"interpret"`` — the same Pallas kernel under ``interpret=True``:
    the CPU tier-1 path, exercising the kernel's structure without a
    TPU (tests/test_fused.py).

Numeric contract: the fused body reuses ``rate_gathered`` verbatim —
the IEEE-exact-op discipline of ``serve/oracle.py`` (fixed-order team
reductions, no FMA-contractible reassociation) survives fusion because
the fused path adds no arithmetic, only different routing of the same
values. Together with the pinned padding slot (slot 0 is a fixed point,
mirroring ``scatter_rows``'s pinned padding row) this makes the fused
window BIT-IDENTICAL to K applications of ``rate_and_apply`` for every
window size — pinned by tests/test_fused.py, not hoped for.

The padding-slot convention is load-bearing: slot 0 always holds the
padding row (``sched.residency`` guarantees it), masked/no-write slots
route their working-set writes to slot 0, and slot 0 is re-pinned after
every step — so the slot mask is derivable on device as
``slot_idx != 0`` and no slab ships it.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core.state import MatchBatch
from analyzer_tpu.core.update import pack_outputs, rate_gathered

#: The working-set slot every masked / non-ratable write routes to, and
#: every padding team slot gathers from. Residency plans put the player
#: table's padding row here unconditionally.
PAD_SLOT = 0

BACKENDS = ("scan", "pallas", "interpret")


def _window_step(ws, xs, cfg: RatingConfig, collect: bool):
    """One superstep against the working set ``ws`` [n_slots, 16].

    ``xs`` is one step of the window slab: slot_idx [B, 2, T] int32,
    winner/mode_id int (any width — widened here like ``expand_step``),
    afk bool. Returns (new_ws, packed outputs | None). This function IS
    the shared math of the scan and Pallas backends — both trace exactly
    these ops, which is what makes them bit-identical to each other and
    (via ``rate_gathered``) to the reference kernel."""
    sidx, winner, mode_id, afk = xs
    mask = sidx != PAD_SLOT
    batch = MatchBatch(
        player_idx=sidx,
        slot_mask=mask,
        winner=winner.astype(jnp.int32),
        mode_id=mode_id.astype(jnp.int32),
        afk=afk,
    )
    rows = ws[sidx]  # the in-window gather: slots, not player rows
    out = rate_gathered(rows, batch, cfg)
    do = out.updated[:, None, None] & mask
    idx = jnp.where(do, sidx, PAD_SLOT)
    new_ws = ws.at[idx].set(out.new_rows)
    # Pin the pad slot (mirrors scatter_rows's pinned padding row): the
    # routed no-write values above are junk, and later steps' masked
    # slots gather slot 0 — it must stay the pristine padding row.
    new_ws = new_ws.at[PAD_SLOT].set(ws[PAD_SLOT])
    return new_ws, (pack_outputs(out) if collect else None)


def _scan_window(ws, slot_idx, winner, mode_id, afk, cfg, collect):
    """The portable fused window: ``lax.scan`` of the shared step body
    over the K-step slab, carrying the working set."""

    def step(carry, xs):
        return _window_step(carry, xs, cfg, collect)

    return jax.lax.scan(step, ws, (slot_idx, winner, mode_id, afk))


def pallas_available() -> bool:
    """Whether the Pallas backends can run in this build."""
    try:  # pragma: no cover - trivially true or false per environment
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    except ImportError:
        return False
    return True


def _pallas_window(ws, slot_idx, winner, mode_id, afk, cfg, collect, interpret):
    """The Pallas fused window: grid = one program per superstep (TPU
    executes the grid sequentially on a core), working set in a VMEM
    scratch buffer that persists across grid steps. HBM -> VMEM happens
    once (step 0 copies the gathered working set in), VMEM -> HBM once
    (the last step copies it out); everything between is on-chip.

    int8/bool slab scalars are widened to int32 *outside* the kernel —
    sub-word blocks hit Mosaic tiling constraints — and the values are
    unchanged, so the traced step math stays bit-identical to the scan
    backend (which widens inside ``_window_step``)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, b, _, t = slot_idx.shape
    ns, w = ws.shape
    cw = 3 + 10 * t  # pack_outputs width

    def kernel(ws_init, sidx_ref, win_ref, mode_ref, afk_ref, *rest):
        if collect:
            ws_out, ys_ref, scratch = rest
        else:
            (ws_out, scratch) = rest
        s = pl.program_id(0)

        @pl.when(s == 0)
        def _():
            scratch[...] = ws_init[...]

        xs = (sidx_ref[0], win_ref[0], mode_ref[0], afk_ref[0] != 0)
        new_ws, ys = _window_step(scratch[...], xs, cfg, collect)
        scratch[...] = new_ws
        if collect:
            ys_ref[0] = ys

        @pl.when(s == pl.num_programs(0) - 1)
        def _():
            ws_out[...] = scratch[...]

    step_spec = lambda shape: pl.BlockSpec(  # noqa: E731 - local spec maker
        (1,) + shape, lambda s: (s,) + (0,) * len(shape)
    )
    out_shape = [jax.ShapeDtypeStruct((ns, w), ws.dtype)]
    out_specs = [pl.BlockSpec((ns, w), lambda s: (0, 0))]
    if collect:
        out_shape.append(jax.ShapeDtypeStruct((k, b, cw), ws.dtype))
        out_specs.append(step_spec((b, cw)))
    res = pl.pallas_call(
        kernel,
        grid=(k,),
        in_specs=[
            pl.BlockSpec((ns, w), lambda s: (0, 0)),
            step_spec((b, 2, t)),
            step_spec((b,)),
            step_spec((b,)),
            step_spec((b,)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((ns, w), ws.dtype)],
        interpret=interpret,
    )(
        ws,
        slot_idx,
        winner.astype(jnp.int32),
        mode_id.astype(jnp.int32),
        afk.astype(jnp.int32),
    )
    if collect:
        return res[0], res[1]
    return res[0], None


def fused_window_table(
    table, slot_rows, slot_idx, winner, mode_id, afk,
    cfg: RatingConfig, collect: bool, backend: str,
):
    """The fused window on a raw table (traced; jitted wrappers below).

    table      [P+1, 16]      the HBM player table
    slot_rows  [n_slots]      plan: slot -> player row (slot 0 = pad row,
                              unused slots = pad row)
    slot_idx   [K, B, 2, T]   plan: per-step batches in slot ids
    winner     [K, B] int     mode_id [K, B] int    afk [K, B] bool

    Returns (table, ys): ys is the ``[K, B, 3+10T]`` packed collect
    tensor (``pack_outputs`` layout) or None. Inert padded steps (all
    slots 0, unsupported mode) produce ys rows the caller drops via its
    slot->match map.
    """
    if backend not in BACKENDS:
        raise ValueError(f"unknown fused backend {backend!r}; use {BACKENDS}")
    ws = table[slot_rows]  # the ONE per-window gather
    if backend == "scan":
        ws, ys = _scan_window(ws, slot_idx, winner, mode_id, afk, cfg, collect)
    else:
        ws, ys = _pallas_window(
            ws, slot_idx, winner, mode_id, afk, cfg, collect,
            interpret=backend == "interpret",
        )
    # The ONE per-window writeback. Duplicate indices (unused slots and
    # slot 0 all map to the padding row) write bit-identical pristine
    # pad-row values — unused slots are never touched and slot 0 is
    # pinned — so the duplicate resolution order cannot matter.
    return table.at[slot_rows].set(ws), ys


_fused_window_jit = jax.jit(
    fused_window_table, static_argnames=("cfg", "collect", "backend")
)

# Hot-loop variant mirroring update.rate_and_apply_step: donates the
# table so XLA writes the window back into the existing HBM buffer.
# ``table = fused_window_step(table, ...)[0]`` loops ONLY.
fused_window_step = jax.jit(
    fused_window_table,
    static_argnames=("cfg", "collect", "backend"),
    donate_argnums=(0,),
)


@partial(jax.jit, static_argnames=("cfg", "collect", "backend"))
def _fused_window_state(state, slot_rows, slot_idx, winner, mode_id, afk,
                        cfg, collect, backend):
    table, ys = fused_window_table(
        state.table, slot_rows, slot_idx, winner, mode_id, afk,
        cfg, collect, backend,
    )
    return dataclasses.replace(state, table=table), ys


def fused_apply_window(
    state, slot_rows, slot_idx, winner, mode_id, afk,
    cfg: RatingConfig, collect: bool = False, backend: str = "scan",
):
    """Non-donating PlayerState-level entry point (tests, one-shot use):
    the caller's state stays valid. The scan runners use the donated
    table-level :func:`fused_window_step` instead."""
    return _fused_window_state(
        state, jnp.asarray(slot_rows), jnp.asarray(slot_idx),
        jnp.asarray(winner), jnp.asarray(mode_id), jnp.asarray(afk),
        cfg, collect, backend,
    )
