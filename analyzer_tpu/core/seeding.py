"""Vectorized TrueSkill seeding for players with no rating yet.

Semantics mirror ``get_trueskill_seed`` (``rater.py:42-62``) exactly:
  * fallback 1 — seed from rank points: take ``max(rank_points_ranked,
    rank_points_blitz)`` where ``None`` **and** ``0`` both mean "missing"
    (``rater.py:45-52``); sigma = UNKNOWN_PLAYER_SIGMA * 2/3 ("more accurate
    than skill tier = more trust"), mu = points + sigma — so the conservative
    estimate mu - sigma equals the seed points exactly (asserted at
    ``worker_test.py:86,95,104,113``).
  * fallback 2 — seed from the skill-tier table: sigma =
    UNKNOWN_PLAYER_SIGMA, mu = vst_points[tier] + sigma (``rater.py:57-60``).

Tensor-path representation: missing rank points are NaN (0 is additionally
treated as missing, as above); missing skill tier is encoded as 0 by the
encoders, which the reference would KeyError on only for tiers outside
-1..29 — the tensor path clamps to the table range instead (the object API in
:mod:`analyzer_tpu.rater` preserves the KeyError contract).
"""

from __future__ import annotations

import jax.numpy as jnp

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core import constants


def trueskill_seed(
    rank_points_ranked: jnp.ndarray,
    rank_points_blitz: jnp.ndarray,
    skill_tier: jnp.ndarray,
    cfg: RatingConfig,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Elementwise seed over any-shaped feature arrays. Returns (mu, sigma)."""
    dtype = rank_points_ranked.dtype
    neg_inf = jnp.asarray(-jnp.inf, dtype)

    rr = jnp.where(
        jnp.isnan(rank_points_ranked) | (rank_points_ranked == 0),
        neg_inf,
        rank_points_ranked,
    )
    rb = jnp.where(
        jnp.isnan(rank_points_blitz) | (rank_points_blitz == 0),
        neg_inf,
        rank_points_blitz,
    )
    rank_points = jnp.maximum(rr, rb)
    has_points = rank_points > neg_inf

    sigma_points = jnp.asarray(cfg.unknown_player_sigma * (2.0 / 3.0), dtype)
    sigma_tier = jnp.asarray(cfg.unknown_player_sigma, dtype)

    table = jnp.asarray(constants.VST_TABLE, dtype)
    tier_idx = jnp.clip(
        skill_tier, constants.MIN_SKILL_TIER, constants.MAX_SKILL_TIER
    ) - constants.MIN_SKILL_TIER
    tier_points = table[tier_idx]

    sigma = jnp.where(has_points, sigma_points, sigma_tier)
    mu = jnp.where(has_points, rank_points + sigma_points, tier_points + sigma_tier)
    return mu, sigma


_host_jit = None


def trueskill_seed_host(
    rank_points_ranked, rank_points_blitz, skill_tier, cfg: RatingConfig
) -> tuple:
    """Seeding for host-side ingest paths, pinned to the CPU backend.
    Numpy in, numpy out.

    :func:`trueskill_seed` called outside jit runs op-by-op on the
    *default* backend — against a remote TPU that is ~20 tiny kernel
    compiles (measured ~12 s through the dev tunnel) just to bake seed
    columns that are about to land back in a host-resident table. Every
    op here (add/compare/select/gather) is bit-identical between the CPU
    and TPU backends, so pinning to CPU costs no parity and makes ingest
    pay milliseconds instead.
    """
    import numpy as np

    import jax

    global _host_jit
    if _host_jit is None:
        _host_jit = jax.jit(trueskill_seed, static_argnums=3)
    # local_devices, not devices: under jax.distributed the global list
    # leads with process 0's devices, and pinning another process's
    # device turns this into a cross-process computation (measured as a
    # Gloo handshake deadline in the 2-process cluster test).
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        mu, sigma = _host_jit(
            jnp.asarray(np.asarray(rank_points_ranked)),
            jnp.asarray(np.asarray(rank_points_blitz)),
            jnp.asarray(np.asarray(skill_tier)),
            cfg,
        )
        return np.asarray(mu), np.asarray(sigma)
