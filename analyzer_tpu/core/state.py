"""HBM-resident rating state and the structure-of-arrays match batch.

The reference's "state" is seven (mu, sigma) column pairs per player row in
MySQL — the shared ``trueskill`` pair plus one pair per game mode
(``worker.py:184-190`` and the 5v5 pair supported at ``rater.py:79-82``) —
plus the seeding features ``rank_points_ranked/blitz`` and ``skill_tier``.
Here the whole player table lives in device memory as dense arrays (a few
million players x 7 f32 column pairs is tens of MB — far below one chip's
HBM), so rating updates are pure gather -> compute -> scatter steps with no
database round-trip.

Conventions (load-bearing):
  * NaN encodes SQL NULL ("never rated") in mu/sigma and rank-point columns.
    The reference branches on ``player.trueskill_mu is not None``
    (``rater.py:115,124,150``); the tensor path branches on ``~isnan(mu)``.
  * Every array has one extra trailing **padding row** (index ``n_players``).
    Empty team slots and masked-out writes target that row, so scatters keep
    static shapes with no dynamic filtering — the TPU-friendly alternative to
    ragged batches.
  * A ``MatchBatch`` packs two teams x ``team_size`` padded slots; 3v3 and
    5v5 share one compiled kernel via the slot mask (SURVEY.md section 7
    "static shapes").
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from analyzer_tpu.core import constants

MAX_TEAM_SIZE = 5


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["mu", "sigma", "rank_points_ranked", "rank_points_blitz", "skill_tier"],
    meta_fields=[],
)
@dataclasses.dataclass
class PlayerState:
    """Dense per-player rating state. Row ``n_players`` is the padding row.

    mu, sigma: ``[P+1, 7]`` — column 0 is the shared rating, columns 1..6 the
    per-mode ratings in :data:`analyzer_tpu.core.constants.MODES` order.
    """

    mu: jnp.ndarray
    sigma: jnp.ndarray
    rank_points_ranked: jnp.ndarray
    rank_points_blitz: jnp.ndarray
    skill_tier: jnp.ndarray

    @property
    def n_players(self) -> int:
        return self.mu.shape[0] - 1

    @property
    def pad_row(self) -> int:
        return self.mu.shape[0] - 1

    @classmethod
    def create(
        cls,
        n_players: int,
        rank_points_ranked: np.ndarray | None = None,
        rank_points_blitz: np.ndarray | None = None,
        skill_tier: np.ndarray | None = None,
        dtype=jnp.float32,
    ) -> "PlayerState":
        """Fresh state: all ratings unset (NaN), features optionally provided.

        Missing rank points are NaN; missing skill tier is 0 (tier 0 seeds to
        1 point, the reference's floor — ``rater.py:15-16``).
        """
        p1 = n_players + 1

        def _feat(x, fill):
            out = np.full((p1,), fill, dtype=np.float64)
            if x is not None:
                out[:n_players] = np.asarray(x, dtype=np.float64)
            return out

        tiers = np.zeros((p1,), dtype=np.int32)
        if skill_tier is not None:
            tiers[:n_players] = np.asarray(skill_tier, dtype=np.int32)
        return cls(
            mu=jnp.full((p1, constants.N_RATING_COLS), jnp.nan, dtype=dtype),
            sigma=jnp.full((p1, constants.N_RATING_COLS), jnp.nan, dtype=dtype),
            rank_points_ranked=jnp.asarray(_feat(rank_points_ranked, np.nan), dtype=dtype),
            rank_points_blitz=jnp.asarray(_feat(rank_points_blitz, np.nan), dtype=dtype),
            skill_tier=jnp.asarray(tiers),
        )


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["player_idx", "slot_mask", "winner", "mode_id", "afk"],
    meta_fields=[],
)
@dataclasses.dataclass
class MatchBatch:
    """A batch of B two-team matches in structure-of-arrays layout.

    player_idx: ``[B, 2, T]`` int32 indices into PlayerState rows (padding
      slots point at the padding row).
    slot_mask:  ``[B, 2, T]`` bool, True for real players.
    winner:     ``[B]`` int32, 0 or 1 — index of the winning team, encoding
      the reference's ``ranks=[int(not r.winner)]`` (``rater.py:144``).
    mode_id:    ``[B]`` int32, index into MODES, or -1 for an unsupported
      mode (the reference logs and skips those, ``rater.py:83-85``).
    afk:        ``[B]`` bool, the reference's ``anyAfk`` gate — True when any
      participant went AFK **or** the match does not have exactly two rosters
      (``rater.py:90-100``).
    """

    player_idx: jnp.ndarray
    slot_mask: jnp.ndarray
    winner: jnp.ndarray
    mode_id: jnp.ndarray
    afk: jnp.ndarray

    @property
    def batch_size(self) -> int:
        return self.player_idx.shape[0]

    @property
    def supported(self) -> jnp.ndarray:
        return self.mode_id >= 0

    @property
    def ratable(self) -> jnp.ndarray:
        """Matches that actually get a rating update (``rater.py:102-106``:
        AFK matches only get quality=0 / any_afk=True side effects)."""
        return self.supported & ~self.afk

    @classmethod
    def pad_to(cls, batch: "MatchBatch", size: int, pad_row: int) -> "MatchBatch":
        """Pads the batch dim to ``size`` with inert matches (all slots
        masked, unsupported mode) so one kernel shape serves ragged tails."""
        b = batch.batch_size
        if b == size:
            return batch
        extra = size - b
        t = batch.player_idx.shape[2]
        return cls(
            player_idx=jnp.concatenate(
                [batch.player_idx, jnp.full((extra, 2, t), pad_row, jnp.int32)]
            ),
            slot_mask=jnp.concatenate(
                [batch.slot_mask, jnp.zeros((extra, 2, t), bool)]
            ),
            winner=jnp.concatenate([batch.winner, jnp.zeros((extra,), jnp.int32)]),
            mode_id=jnp.concatenate(
                [batch.mode_id, jnp.full((extra,), constants.UNSUPPORTED_MODE_ID, jnp.int32)]
            ),
            afk=jnp.concatenate([batch.afk, jnp.zeros((extra,), bool)]),
        )
