"""HBM-resident rating state and the structure-of-arrays match batch.

The reference's "state" is seven (mu, sigma) column pairs per player row in
MySQL — the shared ``trueskill`` pair plus one pair per game mode
(``worker.py:184-190`` and the 5v5 pair supported at ``rater.py:79-82``) —
plus the seeding features ``rank_points_ranked/blitz`` and ``skill_tier``.
Here the whole player table lives in device memory, so rating updates are
pure gather -> compute -> scatter steps with no database round-trip.

Layout (load-bearing for TPU performance): ALL per-player state the kernel
touches is packed into ONE ``[P+1, 16]`` float32 table —

    cols 0..6   mu      (0 = shared ``trueskill``, 1..6 per-mode)
    cols 7..13  sigma   (same order)
    col  14     seed_mu     (precomputed ``get_trueskill_seed`` result)
    col  15     seed_sigma

so one superstep performs a single whole-row gather ``[B, 2, T, 16]`` and a
single whole-row scatter. Per-element (1-D) gathers and take_along_axis
column selects are ~300x slower on TPU than row gathers (the gather unit
moves lane-aligned rows); measured on v5e, the packed layout takes the
superstep from ~1.0 ms to ~microseconds at B=512. Seeding is a pure
function of static features (``rater.py:42-62``), so it is evaluated once
at ingest into cols 14-15 instead of per match in the kernel.

Conventions (load-bearing):
  * NaN encodes SQL NULL ("never rated") in mu/sigma columns. The reference
    branches on ``player.trueskill_mu is not None`` (``rater.py:115,124``);
    the tensor path branches on ``~isnan``.
  * Every array has one extra trailing **padding row** (index ``n_players``).
    Empty team slots and masked-out writes target that row, so scatters keep
    static shapes with no dynamic filtering.
  * A ``MatchBatch`` packs two teams x ``team_size`` padded slots; 3v3 and
    5v5 share one compiled kernel via the slot mask (SURVEY.md section 7
    "static shapes").
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from analyzer_tpu.core import constants

MAX_TEAM_SIZE = 5

# Packed-table column layout.
N_COLS = constants.N_RATING_COLS  # 7: shared + 6 modes
MU_LO, MU_HI = 0, N_COLS
SIGMA_LO, SIGMA_HI = N_COLS, 2 * N_COLS
COL_SEED_MU = 2 * N_COLS
COL_SEED_SIGMA = 2 * N_COLS + 1
TABLE_WIDTH = 2 * N_COLS + 2  # 16


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["table", "rank_points_ranked", "rank_points_blitz", "skill_tier"],
    meta_fields=["seed_cfg"],
)
@dataclasses.dataclass
class PlayerState:
    """Dense per-player rating state. Row ``n_players`` is the padding row.

    ``table``: ``[P+1, 16]`` packed as documented in the module docstring.
    The raw seed features are kept for ingest/debug (they are NOT read by
    the rating kernel — seeds are precomputed into the table).

    ``seed_cfg`` records the RatingConfig whose UNKNOWN_PLAYER_SIGMA baked
    the seed columns; the rating kernel refuses to run with a different
    config (the mismatch would silently ignore env overrides on the tensor
    path while the object API honors them). None = unchecked (raw loads).
    """

    table: jnp.ndarray
    rank_points_ranked: jnp.ndarray
    rank_points_blitz: jnp.ndarray
    skill_tier: jnp.ndarray
    seed_cfg: object = None

    # Views used by the object API, tests, and checkpointing.
    @property
    def mu(self) -> jnp.ndarray:
        return self.table[:, MU_LO:MU_HI]

    @property
    def sigma(self) -> jnp.ndarray:
        return self.table[:, SIGMA_LO:SIGMA_HI]

    @property
    def seed_mu(self) -> jnp.ndarray:
        return self.table[:, COL_SEED_MU]

    @property
    def seed_sigma(self) -> jnp.ndarray:
        return self.table[:, COL_SEED_SIGMA]

    @property
    def n_players(self) -> int:
        return self.table.shape[0] - 1

    @property
    def pad_row(self) -> int:
        return self.table.shape[0] - 1

    @classmethod
    def create(
        cls,
        n_players: int,
        rank_points_ranked: np.ndarray | None = None,
        rank_points_blitz: np.ndarray | None = None,
        skill_tier: np.ndarray | None = None,
        cfg=None,
        dtype=jnp.float32,
    ) -> "PlayerState":
        """Fresh state: all ratings unset (NaN), seeds precomputed from the
        features per ``get_trueskill_seed`` semantics (``rater.py:42-62``).

        Missing rank points are NaN; missing skill tier is 0 (tier 0 seeds
        to 1 point, the reference's floor — ``rater.py:15-16``).
        """
        from analyzer_tpu.config import RatingConfig
        from analyzer_tpu.core.seeding import trueskill_seed_host

        cfg = cfg or RatingConfig()
        p1 = n_players + 1
        np_dtype = np.dtype(dtype)

        def _feat(x, fill):
            out = np.full((p1,), fill, dtype=np.float64)
            if x is not None:
                out[:n_players] = np.asarray(x, dtype=np.float64)
            return out.astype(np_dtype)

        tiers = np.zeros((p1,), dtype=np.int32)
        if skill_tier is not None:
            tiers[:n_players] = np.asarray(skill_tier, dtype=np.int32)

        rr_np = _feat(rank_points_ranked, np.nan)
        rb_np = _feat(rank_points_blitz, np.nan)
        # Seeds bake on the CPU backend: op-by-op remote-TPU dispatch is
        # pure fixed overhead for a host-resident table (seeding.py).
        seed_mu, seed_sigma = trueskill_seed_host(rr_np, rb_np, tiers, cfg)

        table = np.full((p1, TABLE_WIDTH), np.nan, dtype=np_dtype)
        table[:, COL_SEED_MU] = seed_mu
        table[:, COL_SEED_SIGMA] = seed_sigma
        return cls(
            table=jnp.asarray(table),
            rank_points_ranked=jnp.asarray(rr_np),
            rank_points_blitz=jnp.asarray(rb_np),
            skill_tier=jnp.asarray(tiers),
            seed_cfg=cfg,
        )

    def set_rating(self, row: int, col: int, mu: float, sigma: float) -> "PlayerState":
        """Returns a copy with one (mu, sigma) pair written — ingest/tests."""
        table = (
            self.table.at[row, MU_LO + col].set(mu).at[row, SIGMA_LO + col].set(sigma)
        )
        return dataclasses.replace(self, table=table)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["player_idx", "slot_mask", "winner", "mode_id", "afk"],
    meta_fields=[],
)
@dataclasses.dataclass
class MatchBatch:
    """A batch of B two-team matches in structure-of-arrays layout.

    player_idx: ``[B, 2, T]`` int32 indices into PlayerState rows (padding
      slots point at the padding row).
    slot_mask:  ``[B, 2, T]`` bool, True for real players.
    winner:     ``[B]`` int32, 0 or 1 — index of the winning team, encoding
      the reference's ``ranks=[int(not r.winner)]`` (``rater.py:144``).
    mode_id:    ``[B]`` int32, index into MODES, or -1 for an unsupported
      mode (the reference logs and skips those, ``rater.py:83-85``).
    afk:        ``[B]`` bool, the reference's ``anyAfk`` gate — True when any
      participant went AFK **or** the match does not have exactly two rosters
      (``rater.py:90-100``).
    """

    player_idx: jnp.ndarray
    slot_mask: jnp.ndarray
    winner: jnp.ndarray
    mode_id: jnp.ndarray
    afk: jnp.ndarray

    @property
    def batch_size(self) -> int:
        return self.player_idx.shape[0]

    @property
    def supported(self) -> jnp.ndarray:
        return self.mode_id >= 0

    @property
    def ratable(self) -> jnp.ndarray:
        """Matches that actually get a rating update (``rater.py:102-106``:
        AFK matches only get quality=0 / any_afk=True side effects)."""
        return self.supported & ~self.afk

    @classmethod
    def pad_to(cls, batch: "MatchBatch", size: int, pad_row: int) -> "MatchBatch":
        """Pads the batch dim to ``size`` with inert matches (all slots
        masked, unsupported mode) so one kernel shape serves ragged tails."""
        b = batch.batch_size
        if b == size:
            return batch
        extra = size - b
        t = batch.player_idx.shape[2]
        return cls(
            player_idx=jnp.concatenate(
                [batch.player_idx, jnp.full((extra, 2, t), pad_row, jnp.int32)]
            ),
            slot_mask=jnp.concatenate(
                [batch.slot_mask, jnp.zeros((extra, 2, t), bool)]
            ),
            winner=jnp.concatenate([batch.winner, jnp.zeros((extra,), jnp.int32)]),
            mode_id=jnp.concatenate(
                [batch.mode_id, jnp.full((extra,), constants.UNSUPPORTED_MODE_ID, jnp.int32)]
            ),
            afk=jnp.concatenate([batch.afk, jnp.zeros((extra,), bool)]),
        )
