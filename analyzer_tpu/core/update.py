"""The batched per-match rating step: gather -> rate -> scatter.

This module composes the full semantics of the reference's ``rate_match``
(``rater.py:69-169``) as one jit-compiled pure function over a
:class:`~analyzer_tpu.core.state.PlayerState` and a
:class:`~analyzer_tpu.core.state.MatchBatch`:

  1. prior resolution — shared prior from player state, else the
     (precomputed) seed (``rater.py:114-121``); queue-specific prior from
     the mode column, else the shared prior (``rater.py:123-132``);
  2. match quality from the **queue-specific** matchup — the reference's
     comment says "shared" but its code passes ``matchup`` (``rater.py:140-141``);
     we preserve the code's behavior;
  3. the shared update, written to column 0, with the per-participant
     ``trueskill_delta`` = change of the conservative estimate mu - sigma,
     or 0 for a first-ever rating (``rater.py:143-157``);
  4. the queue-specific update, written to the mode column (``rater.py:159-169``);
  5. gating — unsupported modes mutate nothing (``rater.py:83-85``); AFK /
     invalid-roster matches get quality=0 and any_afk=True but **no** rating
     update (``rater.py:90-106``).

TPU shape discipline: the state is touched with exactly ONE whole-row
gather (``table[idx] -> [B, 2, T, 16]``) and ONE whole-row scatter of the
modified rows. Column selection uses one-hot reductions, never per-element
gathers — measured ~300x faster on v5e (see state.py docstring). Scattering
full rows is correct because a superstep is conflict-free: each player row
is written by at most one match, so untouched columns rewrite their own
just-gathered values.

Measured cost split on v5e (B=512, P=1M, honest fetch-timed): gather+all
compute ~35 us/superstep; the row scatter ~370 us and dominates. All XLA
scatter variants (set/add, unique_indices, promise_in_bounds, pre-sorted)
measure the same — the lowering serializes ~72 ns/row. The round-2
head-to-head (``experiments/scatter_floor.py``, BASELINE.md "Scatter
floor") measured the lane-aligned alternatives and the production path
WINS: a [P,128] table costs ~470 ns/row under XLA and ~380-410 ns/row
under a Pallas per-row DMA ring (8-32 copies in flight — descriptor-issue
bound, 512B moved per 64B updated), and Mosaic still rejects DMA on the
native 16-float rows (128-lane alignment). No isolated scatter beats the
floor — so :mod:`analyzer_tpu.core.fused` stops paying it per STEP:
a window of K conflict-free supersteps keeps every touched row resident
in a working set across the whole window (gathered from the table once,
written back once), turning the ~72 ns/row-per-step serialization into
~72 ns/row-per-WINDOW for rows that recur within the window — the
common case, since active players appear in many consecutive steps
(docs/kernels.md has the full design and the VMEM budget math). The
per-step floor below remains the bound for the reference kernel and for
rows that appear once per window.

Correctness precondition: no player index may appear twice among the ratable
matches of one batch (the scatters would collide). The scheduler in
:mod:`analyzer_tpu.sched` constructs batches with that property; a debug
helper here asserts it (SURVEY.md section 5.2: race detection is
correctness-critical on TPU where the reference just raced through MySQL).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.core import constants
from analyzer_tpu.core.state import (
    COL_SEED_MU,
    COL_SEED_SIGMA,
    MU_HI,
    MU_LO,
    N_COLS,
    SIGMA_HI,
    SIGMA_LO,
    MatchBatch,
    PlayerState,
)
from analyzer_tpu.ops import trueskill as ts


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "quality",
        "shared_mu",
        "shared_sigma",
        "delta",
        "mode_mu",
        "mode_sigma",
        "any_afk",
        "write_quality",
        "updated",
        "new_rows",
    ],
    meta_fields=[],
)
@dataclasses.dataclass
class RateOutputs:
    """Per-match / per-slot outputs mirroring the reference's writes.

    quality       [B]       -> match.trueskill_quality (0 for AFK matches)
    shared_mu/.._sigma [B,2,T] -> participant.trueskill_mu/sigma snapshot
    delta         [B,2,T]   -> participant.trueskill_delta
    mode_mu/.._sigma   [B,2,T] -> participant_items.trueskill_<mode>_mu/sigma
    any_afk       [B]       -> participant_items.any_afk (per participant)
    write_quality [B]       whether quality/any_afk are written at all
                            (False for unsupported modes and batch padding)
    updated       [B]       whether ratings were written (ratable matches)
    new_rows      [B,2,T,W] the fully-updated state rows, ready to scatter
    """

    quality: jnp.ndarray
    shared_mu: jnp.ndarray
    shared_sigma: jnp.ndarray
    delta: jnp.ndarray
    mode_mu: jnp.ndarray
    mode_sigma: jnp.ndarray
    any_afk: jnp.ndarray
    write_quality: jnp.ndarray
    updated: jnp.ndarray
    new_rows: jnp.ndarray


def _mode_onehot(mode_id: jnp.ndarray, dtype) -> jnp.ndarray:
    """[B, N_COLS] one-hot of the mode's rating column (mode i -> col i+1;
    col 0 is the shared rating). Unsupported (-1) clamps to col 1; callers
    must mask those matches out (they never write state)."""
    col = jnp.clip(mode_id, 0, None) + 1
    return (col[:, None] == jnp.arange(N_COLS)[None, :]).astype(dtype)


def rate_batch(state: PlayerState, batch: MatchBatch, cfg: RatingConfig) -> RateOutputs:
    """Computes all rating outputs for a batch without touching the state."""
    if (
        state.seed_cfg is not None
        and state.seed_cfg.unknown_player_sigma != cfg.unknown_player_sigma
    ):
        # Trace-time check (both are static): the seed columns were baked
        # with state.seed_cfg; rating with a different config would silently
        # seed unrated players with the wrong UNKNOWN_PLAYER_SIGMA. Only
        # that field feeds the seed columns (core/seeding.py), so
        # dynamics-only changes (e.g. a TAU env override on a loaded
        # checkpoint) are legitimate and pass.
        raise ValueError(
            f"state seeds were built with UNKNOWN_PLAYER_SIGMA="
            f"{state.seed_cfg.unknown_player_sigma}, but rate_batch was "
            f"called with {cfg.unknown_player_sigma}; rebuild the state via "
            "PlayerState.create(..., cfg=cfg)"
        )
    rows = state.table[batch.player_idx]  # [B,2,T,W] — the ONE gather
    return rate_gathered(rows, batch, cfg)


def rate_gathered(
    rows: jnp.ndarray, batch: MatchBatch, cfg: RatingConfig
) -> RateOutputs:
    """:func:`rate_batch` on pre-gathered state rows ``[B,2,T,W]``.

    Split out so the sharded-table mesh path
    (:mod:`analyzer_tpu.parallel.mesh`) can assemble ``rows`` from per-shard
    contributions (psum over the mesh) instead of a full-table gather. The
    caller is responsible for the seed_cfg compatibility check."""
    dtype = rows.dtype
    mask = batch.slot_mask

    mu_cols = rows[..., MU_LO:MU_HI]  # [B,2,T,C]
    sigma_cols = rows[..., SIGMA_LO:SIGMA_HI]
    seed_mu = rows[..., COL_SEED_MU]
    seed_sigma = rows[..., COL_SEED_SIGMA]

    shared_mu_p = mu_cols[..., 0]
    shared_sigma_p = sigma_cols[..., 0]

    onehot = _mode_onehot(batch.mode_id, dtype)  # [B,C]
    oh = onehot[:, None, None, :]  # [B,1,1,C]
    # One-hot column select; NaN-safe (NaN * 0 is avoided via where).
    q_mu_p = jnp.where(oh > 0, mu_cols, 0.0).sum(-1)
    q_sigma_p = jnp.where(oh > 0, sigma_cols, 0.0).sum(-1)
    had_mode = ~jnp.isnan(jnp.where(oh > 0, mu_cols, 0.0)).any(-1)

    had_shared = ~jnp.isnan(shared_mu_p)
    mu_sh = jnp.where(had_shared, shared_mu_p, seed_mu)
    sigma_sh = jnp.where(had_shared, shared_sigma_p, seed_sigma)

    mu_q = jnp.where(had_mode, q_mu_p, mu_sh)
    sigma_q = jnp.where(had_mode, q_sigma_p, sigma_sh)

    quality = ts.quality(mu_q, sigma_q, mask, cfg)  # queue matchup quirk
    new_sh_mu, new_sh_sigma = ts.two_team_update(mu_sh, sigma_sh, mask, batch.winner, cfg)
    new_q_mu, new_q_sigma = ts.two_team_update(mu_q, sigma_q, mask, batch.winner, cfg)

    delta = jnp.where(
        had_shared & mask,
        (new_sh_mu - new_sh_sigma) - (mu_sh - sigma_sh),
        0.0,
    )

    # Assemble the updated rows: col 0 <- shared posterior, mode col <-
    # queue posterior, everything else keeps its gathered value (incl. NaN
    # never-rated markers and the seed columns).
    shared_hot = (jnp.arange(N_COLS) == 0)[None, None, None, :]
    mode_hot = oh > 0
    new_mu_cols = jnp.where(shared_hot, new_sh_mu[..., None], mu_cols)
    new_mu_cols = jnp.where(mode_hot, new_q_mu[..., None], new_mu_cols)
    new_sigma_cols = jnp.where(shared_hot, new_sh_sigma[..., None], sigma_cols)
    new_sigma_cols = jnp.where(mode_hot, new_q_sigma[..., None], new_sigma_cols)
    new_rows = jnp.concatenate(
        [new_mu_cols, new_sigma_cols, rows[..., 2 * N_COLS :]], axis=-1
    )

    ratable = batch.ratable
    return RateOutputs(
        quality=jnp.where(ratable, quality, 0.0),
        shared_mu=new_sh_mu,
        shared_sigma=new_sh_sigma,
        delta=delta,
        mode_mu=new_q_mu,
        mode_sigma=new_q_sigma,
        any_afk=batch.supported & batch.afk,
        write_quality=batch.supported,
        updated=ratable,
        new_rows=new_rows,
    )


def scatter_rows(
    state: PlayerState,
    player_idx: jnp.ndarray,
    slot_mask: jnp.ndarray,
    updated: jnp.ndarray,
    new_rows: jnp.ndarray,
) -> PlayerState:
    """The ONE whole-row scatter: masked / non-ratable slots are routed to
    the padding row, so shapes stay static and no collision can occur as
    long as the batch is conflict-free. (The sharded-table mesh path in
    :mod:`analyzer_tpu.parallel.mesh` instead scatters host-precomputed
    compacted per-shard row lists — see its ``build_routing``.)

    The padding row is RE-PINNED to its pre-step value after the scatter.
    Without the pin, every no-write slot dumps its (per-slot, differing)
    ``new_rows`` into the padding row through the duplicate-index scatter,
    and XLA's duplicate resolution order is unspecified — so the padding
    row held nondeterministic junk that later steps' masked slots then
    GATHERED, leaking into the masked-slot fields of the collected
    outputs. Pinning makes the padding row a fixed point (its seed
    columns stay the baked pad seeds forever), which both kills that
    nondeterminism and is what lets the fused window kernel
    (:mod:`analyzer_tpu.core.fused`) reproduce the reference bit for bit:
    its VMEM pad slot is pinned the same way."""
    do = updated[:, None, None] & slot_mask
    idx = jnp.where(do, player_idx, state.pad_row)
    pad_prev = state.table[state.pad_row]
    table = state.table.at[idx].set(new_rows).at[state.pad_row].set(pad_prev)
    return dataclasses.replace(state, table=table)


def apply_outputs(
    state: PlayerState, batch: MatchBatch, out: RateOutputs
) -> PlayerState:
    """Scatters the updated rows into the player table."""
    return scatter_rows(
        state, batch.player_idx, batch.slot_mask, out.updated, out.new_rows
    )


def rate_and_apply(
    state: PlayerState, batch: MatchBatch, cfg: RatingConfig
) -> tuple[PlayerState, RateOutputs]:
    """One superstep: rate a conflict-free batch and commit the posteriors."""
    out = rate_batch(state, batch, cfg)
    return apply_outputs(state, batch, out), out


def pack_outputs(out: RateOutputs) -> jnp.ndarray:
    """Packs the collectable per-match outputs into ONE ``[B, 3 + 10T]``
    f32 tensor — layout: quality, any_afk, updated, then five ``[2T]``
    blocks (shared_mu, shared_sigma, delta, mode_mu, mode_sigma). The
    ``[B,2,T,16]`` new_rows stay out (scatter plumbing that would
    dominate memory); one tensor = one D2H fetch per chunk. Shared by
    the reference scan (``sched.runner._scan_chunk``) and the fused
    window kernel (:mod:`analyzer_tpu.core.fused`) so the collect layout
    — and its bit pattern — cannot drift between kernels;
    ``sched.runner._gather_outputs`` unpacks it."""
    b = out.quality.shape[0]
    f32 = out.shared_mu.dtype
    return jnp.concatenate(
        [
            out.quality[:, None].astype(f32),
            out.any_afk[:, None].astype(f32),
            out.updated[:, None].astype(f32),
            out.shared_mu.reshape(b, -1),
            out.shared_sigma.reshape(b, -1),
            out.delta.reshape(b, -1),
            out.mode_mu.reshape(b, -1),
            out.mode_sigma.reshape(b, -1),
        ],
        axis=1,
    )


rate_and_apply_jit = jax.jit(rate_and_apply, static_argnames=("cfg",))

# Hot-loop variant: donates the state so XLA scatters into the existing HBM
# buffers instead of allocating a fresh table per superstep. Use in
# ``state = rate_and_apply_step(state, batch, cfg)[0]`` loops ONLY — the
# passed-in state is invalidated. (The scan runner in sched.runner donates
# its whole chunk the same way.)
rate_and_apply_step = jax.jit(
    rate_and_apply, static_argnames=("cfg",), donate_argnums=(0,)
)


def rate_and_apply_checked(
    state: PlayerState, batch: MatchBatch, cfg: RatingConfig
) -> tuple[PlayerState, RateOutputs]:
    """Entry point for *untrusted* batches (anything not produced by the
    scheduler in :mod:`analyzer_tpu.sched`, which constructs conflict-free
    supersteps by construction): host-side race check first, then the jitted
    step. SURVEY.md section 5.2 — scatter collisions must be impossible or
    detected."""
    check_conflict_free(batch)
    return rate_and_apply_jit(state, batch, cfg)


def check_conflict_free(batch: MatchBatch) -> None:
    """Debug-mode race detector (SURVEY.md section 5.2): asserts no player
    appears in two ratable matches of one batch. Host-side, not jittable —
    call it on untrusted batches before the jitted step (or use
    :func:`rate_and_apply_checked`)."""
    import numpy as np

    idx = np.asarray(batch.player_idx)
    mask = np.asarray(batch.slot_mask) & np.asarray(batch.ratable)[:, None, None]
    flat = idx[mask]
    uniq, counts = np.unique(flat, return_counts=True)
    dup = uniq[counts > 1]
    if dup.size:
        raise ValueError(
            f"batch is not conflict-free: player rows {dup[:16].tolist()} appear "
            "in multiple ratable matches; scatters would collide"
        )


def check_window_conflict_free(
    player_idx, ratable, pad_row=None, slot_mask=None
) -> None:
    """Window-level race detector: :func:`check_conflict_free` validates a
    SINGLE batch, but a fused window dispatch (:mod:`analyzer_tpu.core.fused`)
    commits K supersteps in one call — an untrusted window must have every
    step conflict-free before any of them runs, or the mid-window working
    set silently rates from a half-written row. ``player_idx`` is the
    ``[K, B, 2, T]`` window, ``ratable`` the ``[K, B]`` write gate;
    ``slot_mask`` defaults to the compact-feed invariant
    ``player_idx != pad_row`` (pass one of the two)."""
    import numpy as np

    idx = np.asarray(player_idx)
    ratable = np.asarray(ratable)
    if slot_mask is None:
        if pad_row is None:
            raise TypeError(
                "check_window_conflict_free needs pad_row or slot_mask to "
                "tell padding slots from real players"
            )
        mask = idx != pad_row
    else:
        mask = np.asarray(slot_mask)
    live = mask & ratable[:, :, None, None]
    for s in range(idx.shape[0]):
        flat = idx[s][live[s]]
        uniq, counts = np.unique(flat, return_counts=True)
        dup = uniq[counts > 1]
        if dup.size:
            raise ValueError(
                f"window step {s} is not conflict-free: player rows "
                f"{dup[:16].tolist()} appear in multiple ratable matches "
                "of one superstep; the fused working-set writes would "
                "collide"
            )


def check_skill_tiers(state: PlayerState) -> None:
    """Debug check matching the reference's KeyError contract for tiers
    outside -1..29 (``rater.py:60``): the jitted seed path clamps silently
    for shape-stability, so run this on ingested state to surface bad rows."""
    import numpy as np

    tiers = np.asarray(state.skill_tier[: state.n_players])
    bad = np.where(
        (tiers < constants.MIN_SKILL_TIER) | (tiers > constants.MAX_SKILL_TIER)
    )[0]
    if bad.size:
        raise KeyError(
            f"player rows {bad[:16].tolist()} have skill_tier outside "
            f"[{constants.MIN_SKILL_TIER}, {constants.MAX_SKILL_TIER}] "
            f"(values {tiers[bad[:16]].tolist()}); the reference raises KeyError "
            "for these (rater.py:60)"
        )
