from analyzer_tpu.core.constants import (
    MODES,
    MODE_TO_ID,
    N_RATING_COLS,
    RATING_COLUMNS,
    VST_POINTS,
    VST_TABLE,
)
from analyzer_tpu.core.seeding import trueskill_seed
from analyzer_tpu.core.state import MAX_TEAM_SIZE, MatchBatch, PlayerState
from analyzer_tpu.core.update import (
    RateOutputs,
    apply_outputs,
    check_conflict_free,
    check_skill_tiers,
    rate_and_apply,
    rate_and_apply_checked,
    rate_and_apply_jit,
    rate_and_apply_step,
    rate_batch,
)

__all__ = [
    "MODES",
    "MODE_TO_ID",
    "N_RATING_COLS",
    "RATING_COLUMNS",
    "VST_POINTS",
    "VST_TABLE",
    "trueskill_seed",
    "MAX_TEAM_SIZE",
    "MatchBatch",
    "PlayerState",
    "RateOutputs",
    "apply_outputs",
    "check_conflict_free",
    "check_skill_tiers",
    "rate_and_apply",
    "rate_and_apply_checked",
    "rate_and_apply_jit",
    "rate_and_apply_step",
    "rate_batch",
]
