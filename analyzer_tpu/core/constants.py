"""Domain constants: game modes and the skill-tier → points table.

Semantics mirror the reference:
  * mode → rating-column mapping, ``rater.py:70-85`` — six supported modes;
    anything else is unratable and must leave the match untouched.
  * ``vst_points`` skill-tier table, ``rater.py:14-27`` — piecewise-linear map
    from Vainglory skill tier (-1..29) to average tier points. The reference
    comment claims "-1 - 30" but the table only covers -1..29; tier 30 raises
    KeyError there (``rater.py:60``), and we preserve that contract in the
    object API while the tensor path clamps (with a debug check).
"""

from __future__ import annotations

import numpy as np

# Order is load-bearing: mode_id is the index into this tuple, and column 1+i
# of the player-state arrays is mode i (column 0 is the shared rating).
MODES: tuple[str, ...] = (
    "casual",
    "ranked",
    "blitz",
    "br",
    "5v5_casual",
    "5v5_ranked",
)
MODE_TO_ID: dict[str, int] = {m: i for i, m in enumerate(MODES)}
N_MODES = len(MODES)
# Rating-state columns: 0 = shared "trueskill", 1..6 = "trueskill_<mode>".
N_RATING_COLS = 1 + N_MODES
SHARED_COL = 0

# Column-name prefixes as persisted by the reference schema (worker.py:184-190
# plus the 5v5 pair rater.py:79-82 supports but worker.py never eager-loads).
RATING_COLUMNS: tuple[str, ...] = ("trueskill",) + tuple(
    f"trueskill_{m}" for m in MODES
)

UNSUPPORTED_MODE_ID = -1

MIN_SKILL_TIER = -1
MAX_SKILL_TIER = 29


def _build_vst_points() -> dict[int, float]:
    """Recomputes the tier-points table with the reference's own recurrence
    (``rater.py:14-27``): tiers -1,0 → 1; then segment widths 109+1/11 (tiers
    1-11), 50 (12-15), 66+2/3 (16-24), 133+1/3 (25-27), 200 (28-29), each tier
    placed at the segment midpoint (c + 0.5). Out-of-range tiers: the object
    API raises KeyError like the reference; the tensor path clamps for shape
    stability, with ``core.update.check_skill_tiers`` as the ingest-time
    debug check that surfaces bad rows."""
    pts: dict[int, float] = {-1: 1.0, 0: 1.0}
    for c in range(1, 12):
        pts[c] = (109 + 1 / 11) * (c + 0.5)
    for c in range(1, 5):
        pts[11 + c] = pts[11] + 50 * (c + 0.5)
    for c in range(1, 10):
        pts[15 + c] = pts[15] + (66 + 2 / 3) * (c + 0.5)
    for c in range(1, 4):
        pts[24 + c] = pts[24] + (133 + 1 / 3) * (c + 0.5)
    for c in range(1, 3):
        pts[27 + c] = pts[27] + 200 * (c + 0.5)
    return pts


VST_POINTS: dict[int, float] = _build_vst_points()

# Dense lookup for the tensor path: VST_TABLE[tier + 1] == VST_POINTS[tier].
VST_TABLE: np.ndarray = np.array(
    [VST_POINTS[t] for t in range(MIN_SKILL_TIER, MAX_SKILL_TIER + 1)],
    dtype=np.float64,
)
