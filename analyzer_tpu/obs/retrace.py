"""Retrace accounting: ``jax.monitoring`` hooks + tracked jitted
entrypoints.

graftlint's GL004/GL007 flag the retrace *hazards* statically; this
module measures the *events*. Two complementary sources:

  * **Global compile events** — ``install_jax_hooks`` registers a
    ``jax.monitoring`` duration listener. Every jaxpr trace bumps
    ``jax.retraces_total`` (a retrace IS a fresh trace of some jitted
    function past its first), every XLA backend compile bumps
    ``jax.backend_compiles_total`` with the duration histogrammed — so a
    service worker that starts recompiling mid-flight shows a moving
    counter, not just a latency regression.
  * **Per-entrypoint cache sizes** — hot jitted functions register
    themselves via :func:`track_jit` (e.g. ``sched._scan_chunk`` at
    module import). :func:`retrace_counts` reads each function's live
    ``_cache_size()``: the number of distinct (shape, dtype, static-arg)
    variants it compiled. A dtype flip on a warmed entrypoint shows up as
    that entry incrementing — the measurable form of the GL004 hazard,
    and exactly what ``tests/test_service.py::TestCompileChurn`` asserts
    by hand today.

jax is imported lazily (inside the install/count calls): the obs package
stays importable in jax-free contexts (lint tooling, ``cli metrics`` on a
saved snapshot).
"""

from __future__ import annotations

import threading

from analyzer_tpu.obs.registry import get_registry

_lock = threading.Lock()
_installed = False
_tracked: dict[str, object] = {}


def track_jit(name: str, fn):
    """Registers a jitted callable under ``name`` for per-entrypoint
    retrace accounting; returns ``fn`` so call sites can wrap in place:

        _scan_chunk = track_jit("sched._scan_chunk", jax.jit(...))

    Re-registering a name replaces the previous function (module
    reloads)."""
    with _lock:
        _tracked[name] = fn
    return fn


def tracked_names() -> list[str]:
    with _lock:
        return sorted(_tracked)


def retrace_counts() -> dict[str, int]:
    """``{entrypoint: compiled-variant count}`` for every tracked jitted
    function. The count is the live jit cache size — baseline 1 after
    warmup; anything above the warmed ladder's size is a retrace. A
    function that does not expose ``_cache_size`` (older jax, plain
    callables) reports -1 rather than lying with 0."""
    with _lock:
        items = list(_tracked.items())
    out: dict[str, int] = {}
    for name, fn in items:
        size = getattr(fn, "_cache_size", None)
        try:
            out[name] = int(size()) if callable(size) else -1
        except Exception:  # noqa: BLE001 — accounting must not raise
            out[name] = -1
    return out


# jax._src.dispatch.{JAXPR_TRACE_EVENT, BACKEND_COMPILE_EVENT} as
# literals: the listener fires on every compile event and must not pay a
# module lookup there; tests/test_obs.py pins these against the live jax
# so a rename fails loudly instead of silently counting nothing.
JAXPR_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _on_event_duration(event: str, duration: float, **_kwargs) -> None:
    reg = get_registry()
    if event == JAXPR_TRACE_EVENT:
        reg.counter("jax.retraces_total").add(1)
        reg.histogram("jax.trace_seconds").observe(duration)
    elif event == BACKEND_COMPILE_EVENT:
        reg.counter("jax.backend_compiles_total").add(1)
        reg.histogram("jax.backend_compile_seconds").observe(duration)


def install_jax_hooks() -> bool:
    """Registers the ``jax.monitoring`` listeners into the process-wide
    registry. Idempotent; returns True when the hooks are (now)
    installed, False when jax is unavailable.

    Note jax keeps listeners for the life of the process (there is no
    public unregister), so the hook writes through :func:`get_registry`
    at event time — a test that swaps the registry keeps counting into
    the fresh one."""
    global _installed
    with _lock:
        if _installed:
            return True
    try:
        from jax import monitoring
    except ImportError:
        return False
    with _lock:
        if _installed:  # lost the race to another installer
            return True
        monitoring.register_event_duration_secs_listener(_on_event_duration)
        _installed = True
    return True


def jax_hooks_installed() -> bool:
    with _lock:
        return _installed
