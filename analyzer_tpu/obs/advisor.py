"""Tuning advisor: a deterministic rule table over the repo's artifacts.

ROADMAP item 1's rig campaign is a tuning loop — name the dominant
stage at each scale point, turn a knob, re-measure. The telemetry to
answer "which knob" already ships in every artifact the repo emits
(BENCH/SOAK/INGEST/MIGRATE JSON lines, history rings, and now the
profile attribution + roofline ledger); this module is the missing
read side: ``cli tune`` loads whatever artifacts exist, walks a FIXED
rule table in severity order, and emits findings that each

  * name the bottleneck,
  * recommend a concrete knob change — ``fuse_window``, ``hot_rows``,
    prefetch depth, ``plan_windows``, broker admission — and
  * cite the exact evidence series (value + artifact) that triggered
    the rule,

rendered as text or JSON plus a ready-to-paste env/flag snippet.

**Pure, clock-free, deterministic** (graftlint GL046, like the
history/SLO plane's GL032): no wall-clock reads, no randomness, no
dict-order dependence — the same inputs produce a byte-identical
report, so a tuning recommendation can be diffed, committed, and
re-derived on another machine. Peak-magnitude literals are banned here
too; anything roofline-shaped comes pre-computed in the artifacts (the
roofs themselves live in :mod:`analyzer_tpu.obs.hw`).
"""

from __future__ import annotations

import glob
import json
import os

#: Artifact filename families ``gather_inputs`` scans for (sorted, so
#: the newest ``rNN`` sorts last and becomes the family's evidence).
ARTIFACT_GLOBS = (
    "BENCH_*.json",
    "SOAK_*.json",
    "INGEST_BENCH_*.json",
    "MIGRATE_BENCH_*.json",
    "SERVE_BENCH_*.json",
)

#: Evidence thresholds, named so the rule table reads as policy.
IDLE_FRAC_HIGH = 0.4          # device idles >40% of the capture window
FUSED_RATIO_NOT_PAYING = 0.97  # fused/reference >= this = fusion moot
TIER_HIT_RATE_LOW = 0.95
TIER_TAX_HIGH = 1.25           # tiered/resident end-to-end ratio
BANDWIDTH_ROOF_FRAC = 0.5
QUEUE_GROWTH_FACTOR = 2.0      # broker depth last/first over the rings


def load_artifact(path: str) -> dict | None:
    """One artifact's metric line (unwraps the driver's ``{"parsed":
    ...}`` capture shape); None when unreadable — the advisor runs over
    whatever evidence actually loads."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    if "metric" not in data and isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    return data if "metric" in data else None


def family_of(data: dict) -> str:
    metric = str(data.get("metric", ""))
    for prefix, fam in (
        ("soak.", "soak"), ("ingest.", "ingest"), ("migrate.", "migrate"),
        ("serve.", "serve"),
    ):
        if metric.startswith(prefix):
            return fam
    return "bench"


def gather_inputs(paths=(), scan_dir: str | None = None,
                  profile_dir: str | None = None) -> dict:
    """Loads the advisor's evidence. Explicit ``paths`` win; otherwise
    ``scan_dir`` is globbed for the known artifact families. A path
    ending in ``history.json`` (or a flight-dump dir holding one) loads
    as the history rings; ``profile_dir`` attributes a capture dir via
    obs/profview (metrics updates off — the advisor is pure)."""
    from analyzer_tpu.obs.profview import analyze_capture

    names: list[str] = []
    if paths:
        names = sorted(paths)
    elif scan_dir:
        for pattern in ARTIFACT_GLOBS:
            names.extend(glob.glob(os.path.join(scan_dir, pattern)))
        names = sorted(names)
    artifacts = []
    history = None
    for p in names:
        base = p
        if os.path.isdir(p):
            base = os.path.join(p, "history.json")
        if base.endswith("history.json"):
            try:
                with open(base, encoding="utf-8") as f:
                    payload = json.load(f)
                if isinstance(payload, dict) and "series" in payload:
                    history = payload
                    continue
            except (OSError, ValueError):
                continue
        data = load_artifact(p)
        if data is None:
            continue
        artifacts.append(
            {"path": p, "family": family_of(data),
             "metric": str(data.get("metric", "")), "data": data}
        )
    profile = None
    if profile_dir:
        profile = analyze_capture(profile_dir, update_metrics=False)
    return {"artifacts": artifacts, "history": history, "profile": profile}


def _latest(inputs: dict, family: str) -> dict | None:
    """Newest artifact of a family (sorted path order: rNN naming makes
    lexicographic == chronological)."""
    picked = None
    for art in inputs["artifacts"]:
        if art["family"] == family:
            picked = art
    return picked


def _finding(rule, bottleneck, action, evidence, env=None, flags=None):
    return {
        "rule": rule,
        "bottleneck": bottleneck,
        "action": action,
        "evidence": list(evidence),
        "env": dict(env or {}),
        "flags": list(flags or []),
    }


# -- the rule table (evaluated in order; order = severity) --------------

def _rule_ingest_native(inputs):
    art = _latest(inputs, "ingest")
    if art is None:
        return None
    ingest = art["data"].get("ingest") or {}
    if ingest.get("native") is False:
        return _finding(
            "ingest-native-fallback", "ingest decode (python codec)",
            "the columnar native decoder was unavailable and ingest ran "
            "the python codec — rebuild io/_native_csv before tuning "
            "anything else; every downstream number is decode-bound",
            [f"ingest.native=false ({art['path']})"],
        )
    return None


def _rule_migrate_assign(inputs):
    art = _latest(inputs, "migrate")
    if art is None:
        return None
    mig = art["data"].get("migrate") or {}
    if mig.get("assign_native") is False:
        return _finding(
            "migrate-assign-fallback", "backfill assignment (python loop)",
            "the migration's windowed first-fit ran the python fallback "
            "instead of the GIL-released native loop — rebuild "
            "sched/packer.cc; assignment throughput is ~two orders below "
            "the native route",
            [f"migrate.assign_native=false ({art['path']})"],
        )
    return None


def _rule_feed_starved(inputs):
    art = _latest(inputs, "bench")
    if art is None:
        return None
    feed = ((art["data"].get("telemetry") or {}).get("feed")) or {}
    starved = feed.get("starved_total") or 0
    backpressure = feed.get("backpressure_total") or 0
    if starved > 0 and starved >= backpressure:
        return _finding(
            "feed-starved", "host feed (device starved for windows)",
            "the prefetching feed starved at least as often as it "
            "backpressured — the device outran the host; deepen the "
            "committed-slab ring",
            [
                f"feed.starved_total={starved} vs "
                f"feed.backpressure_total={backpressure} ({art['path']})"
            ],
            env={"BENCH_FEED_DEPTH": "4"},
            flags=["cli bench (BENCH_FEED_DEPTH=4)"],
        )
    return None


def _rule_device_idle(inputs):
    art = _latest(inputs, "bench")
    evidence = []
    window = None
    if art is not None:
        roof = art["data"].get("roofline") or {}
        idle = roof.get("device_idle_frac")
        if idle is not None and idle > IDLE_FRAC_HIGH:
            evidence.append(
                f"roofline.device_idle_frac={idle} ({art['path']})"
            )
        fused = art["data"].get("fused") or {}
        if fused.get("window"):
            window = int(fused["window"])
    prof = inputs.get("profile")
    if prof and prof.get("parsed"):
        idle = (prof.get("device") or {}).get("idle_frac")
        if idle is not None and idle > IDLE_FRAC_HIGH:
            evidence.append(
                f"profile device.idle_frac={idle} ({prof['dir']})"
            )
    if not evidence:
        return None
    new_window = (window or 16) * 2
    return _finding(
        "device-idle", "per-dispatch overhead (device idles mid-window)",
        f"the device sat idle more than {int(100 * IDLE_FRAC_HIGH)}% of "
        "the capture window — dispatches are too small to amortize "
        f"launch latency; widen the fused window to {new_window} "
        "supersteps per dispatch",
        evidence,
        env={"BENCH_FUSE_WINDOW": str(new_window)},
        flags=[f"cli bench --fuse-window {new_window}"],
    )


def _rule_dispatch_overhead(inputs):
    art = _latest(inputs, "bench")
    if art is None:
        return None
    roof = art["data"].get("roofline") or {}
    if roof.get("bound_by") != "overhead":
        return None
    return _finding(
        "dispatch-overhead", "per-dispatch fixed cost",
        "the roofline verdict is `overhead` — achieved bandwidth AND "
        "flops both sit under 5% of peak, so neither roof is the "
        "constraint; batch more work per dispatch (fuse window, batch "
        "size) before touching anything bandwidth-shaped",
        [
            f"roofline.bound_by=overhead, frac_of_peak_bw="
            f"{roof.get('frac_of_peak_bw')}, frac_of_peak_flops="
            f"{roof.get('frac_of_peak_flops')} ({art['path']})"
        ],
        env={"BENCH_FUSE_WINDOW": "32"},
        flags=["cli bench --fuse-window 32"],
    )


def _rule_fused_not_paying(inputs):
    art = _latest(inputs, "bench")
    if art is None:
        return None
    fused = art["data"].get("fused") or {}
    ratio = fused.get("min_over_reference")
    if ratio is None or ratio < FUSED_RATIO_NOT_PAYING:
        return None
    window = int(fused.get("window") or 16)
    new_window = window * 2
    return _finding(
        "fused-not-paying", "fused window kernel (no gain over reference)",
        f"fused.min_over_reference={ratio} — the VMEM-resident window "
        "kernel is not beating the reference scan (a ratio ~1.0 can "
        "also mean a silent fallback); widen the window to "
        f"{new_window} so residency amortizes more scatter traffic",
        [f"fused.min_over_reference={ratio}, window={window} "
         f"({art['path']})"],
        env={"BENCH_FUSE_WINDOW": str(new_window)},
        flags=[f"cli bench --fuse-window {new_window}"],
    )


def _rule_tier_thrash(inputs):
    art = _latest(inputs, "bench")
    if art is None:
        return None
    tiered = art["data"].get("tiered") or {}
    hit = tiered.get("hit_rate")
    tax = tiered.get("min_over_resident")
    evidence = []
    if hit is not None and hit < TIER_HIT_RATE_LOW:
        evidence.append(f"tiered.hit_rate={hit} ({art['path']})")
    if tax is not None and tax > TIER_TAX_HIGH:
        evidence.append(f"tiered.min_over_resident={tax} ({art['path']})")
    if not evidence:
        return None
    hot = int(tiered.get("hot_rows") or 0)
    new_hot = hot * 2 if hot else 0
    action = (
        "the hot set is too small for the working set (tier thrash: "
        "promotions on the hot path)"
    )
    env = {}
    flags = []
    if new_hot:
        action += f"; double the hot set to {new_hot} rows"
        env["BENCH_HOT_ROWS"] = str(new_hot)
        flags.append(f"cli bench --hot-rows {new_hot}")
    else:
        action += "; double hot_rows"
    return _finding(
        "tier-thrash", "tiered table (hot-set thrash)", action, evidence,
        env=env, flags=flags,
    )


def _rule_queue_wait(inputs):
    art = _latest(inputs, "soak")
    if art is None:
        return None
    dominant = (
        (art["data"].get("slo") or {}).get("dominant_stage")
        or (art["data"].get("trace") or {}).get("dominant_stage")
    )
    if dominant not in ("queue_wait", "broker_transit"):
        return None
    return _finding(
        "queue-wait-dominant", "broker admission (batches wait in queue)",
        f"the soak's dominant stage is `{dominant}` — matches spend "
        "longer waiting for admission than being processed; partition "
        "the broker / add workers, or lower the admitted rate to what "
        "the dispatch plane sustains",
        [f"slo.dominant_stage={dominant} ({art['path']})"],
        flags=["cli soak --partitions 2 (broker admission)"],
    )


def _rule_queue_growth(inputs):
    hist = inputs.get("history")
    if not hist:
        return None
    for name in sorted(hist.get("series") or {}):
        if not name.startswith("broker.queue_depth"):
            continue
        rows = ((hist["series"][name].get("rings") or {}).get("raw")) or []
        if len(rows) < 2:
            continue
        first, last = rows[0][1], rows[-1][1]
        if first >= 0 and last > max(first, 1) * QUEUE_GROWTH_FACTOR:
            return _finding(
                "queue-depth-growing", "broker admission (backlog growing)",
                f"`{name}` grew {first} -> {last} over the history ring "
                "— admission outpaces drain; throttle producers or add "
                "consume capacity before the backlog turns into "
                "staleness",
                [f"{name}: {first} -> {last} (history rings)"],
                flags=["cli soak --partitions 2 (broker admission)"],
            )
    return None


def _rule_plan_prefix(inputs):
    art = _latest(inputs, "migrate")
    if art is None:
        return None
    mig = art["data"].get("migrate") or {}
    plan = mig.get("plan_windows")
    prefix = mig.get("prefix_windows")
    if not plan or prefix is None or prefix < plan:
        return None
    new_plan = int(plan) * 2
    return _finding(
        "plan-prefix-exhausted", "batch-size planning prefix",
        f"the backfill's batch-size planner consumed its whole "
        f"{plan}-window prefix — the chosen batch size may be keyed to "
        f"an unrepresentative head; widen the prefix to {new_plan} "
        "windows",
        [f"migrate.prefix_windows={prefix} >= plan_windows={plan} "
         f"({art['path']})"],
        env={"BENCH_MIGRATE_PLAN_WINDOWS": str(new_plan)},
    )


def _rule_bandwidth_roof(inputs):
    art = _latest(inputs, "bench")
    if art is None:
        return None
    roof = art["data"].get("roofline") or {}
    frac = roof.get("frac_of_peak_bw")
    if roof.get("bound_by") != "memory" or frac is None \
            or frac < BANDWIDTH_ROOF_FRAC:
        return None
    return _finding(
        "bandwidth-roof", "HBM bandwidth (at the roof)",
        f"the dispatch achieves {round(100 * frac, 1)}% of peak "
        "bandwidth and the verdict is memory-bound — the knobs are "
        "exhausted at this table layout; further gains need fewer bytes "
        "per match (row packing / fused writeback elision), not "
        "scheduling",
        [f"roofline.frac_of_peak_bw={frac}, bound_by=memory "
         f"({art['path']})"],
    )


RULES = (
    _rule_ingest_native,
    _rule_migrate_assign,
    _rule_feed_starved,
    _rule_device_idle,
    _rule_dispatch_overhead,
    _rule_fused_not_paying,
    _rule_tier_thrash,
    _rule_queue_wait,
    _rule_queue_growth,
    _rule_plan_prefix,
    _rule_bandwidth_roof,
)


def advise(inputs: dict) -> dict:
    """The recommendation report: every firing rule, in table order.
    Pure function of its inputs — same artifacts, same bytes."""
    findings = []
    for rule in RULES:
        f = rule(inputs)
        if f is not None:
            findings.append(f)
    env_lines: dict[str, str] = {}
    flag_lines: list[str] = []
    for f in findings:
        for k in sorted(f["env"]):
            env_lines.setdefault(k, f["env"][k])
        for fl in f["flags"]:
            if fl not in flag_lines:
                flag_lines.append(fl)
    snippet = "".join(
        f"export {k}={env_lines[k]}\n" for k in sorted(env_lines)
    ) + "".join(f"# {fl}\n" for fl in flag_lines)
    prof = inputs.get("profile")
    return {
        "artifacts": [
            {"path": a["path"], "family": a["family"], "metric": a["metric"]}
            for a in inputs["artifacts"]
        ],
        "profile": None if prof is None else {
            "dir": prof.get("dir"),
            "parsed": bool(prof.get("parsed")),
            "dominant_kernel": prof.get("dominant_kernel"),
            "device_idle_frac": (prof.get("device") or {}).get("idle_frac"),
        },
        "history": bool(inputs.get("history")),
        "findings": findings,
        "bottleneck": findings[0]["bottleneck"] if findings else None,
        "snippet": snippet,
    }


def render_report(report: dict) -> str:
    """The text render (byte-identical for identical reports)."""
    out = [
        f"tuning advisor: {len(report['findings'])} finding(s) over "
        f"{len(report['artifacts'])} artifact(s)"
        + (", history rings" if report.get("history") else "")
        + (", profile capture" if report.get("profile") else "")
    ]
    for a in report["artifacts"]:
        out.append(f"  input: {a['path']} ({a['family']}: {a['metric']})")
    prof = report.get("profile")
    if prof:
        out.append(
            f"  profile: {prof['dir']} parsed={str(prof['parsed']).lower()}"
            + (f", dominant kernel {prof['dominant_kernel']}"
               if prof.get("dominant_kernel") else "")
        )
    if not report["findings"]:
        out.append("no rule fired — telemetry reads healthy at the "
                   "current knobs")
        return "\n".join(out) + "\n"
    out.append(f"bottleneck: {report['bottleneck']}")
    for i, f in enumerate(report["findings"], 1):
        out.append(f"{i}. [{f['rule']}] {f['bottleneck']}")
        out.append(f"   {f['action']}")
        for ev in f["evidence"]:
            out.append(f"   evidence: {ev}")
        for k in sorted(f["env"]):
            out.append(f"   set: {k}={f['env'][k]}")
        for fl in f["flags"]:
            out.append(f"   via: {fl}")
    if report["snippet"]:
        out.append("env/flag snippet:")
        for line in report["snippet"].rstrip("\n").split("\n"):
            out.append(f"  {line}")
    return "\n".join(out) + "\n"
