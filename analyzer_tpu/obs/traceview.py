"""Trace analyzer: reconstruct per-match / per-batch timelines from a
trace-events export.

Input is the Chrome trace-event JSONL the tracer exports (``cli rate
--trace-events``, ``cli soak --trace-events``, or the ``trace.jsonl``
inside a flight-recorder dump directory). With causal tracing enabled
(obs/tracectx.py) those events carry the ids that make reconstruction
possible:

  * ``trace.enqueue`` instants anchor each match's timeline at the
    moment it entered the broker;
  * ``batch.assemble`` instants record which match traces joined which
    batch (``batch`` id + ``members`` + ``enqueues``);
  * every span the batch's pipeline emitted — encode, pack, the feed
    thread's materialize/transfer, dispatch, fetch, commit — carries
    ``args.trace`` = the batch id;
  * ``view.publish`` instants mark the version that made the batch's
    rows serve-visible.

:func:`build_model` joins those into a :class:`TraceModel`;
:func:`match_report` / :func:`batch_report` decompose one journey into
the operator-facing stages (queue wait, encode, pack, feed staging,
H2D, dispatch, fetch, commit, publish lag); :func:`critical_path`
aggregates a window of batches and names the dominant stage — the
number a staleness page actually needs. ``cli trace`` renders all
three; the soak driver embeds :func:`critical_path` into the SOAK
artifact. Stdlib-only, like the rest of the exposition layer.
"""

from __future__ import annotations

import json
import os

#: Span name -> stage bucket of the operator-facing decomposition.
#: ``batch.compute`` / ``batch.dispatch`` are ENQUEUE cost (dispatch);
#: device time surfaces host-side in ``batch.fetch``; the tier manager's
#: promote/demote traffic is feed-thread staging work.
STAGE_OF = {
    "batch.encode": "encode",
    "batch.pack": "pack",
    "batch.chain": "dispatch",
    "batch.dispatch": "dispatch",
    "batch.compute": "dispatch",
    "feed.materialize": "feed_staging",
    "tier.promote": "feed_staging",
    "tier.demote": "feed_staging",
    "feed.transfer": "h2d",
    "batch.fetch": "fetch",
    "batch.write_back": "commit",
    "batch.commit": "commit",
}

#: Stage order for reports (queue wait first, publish lag last — the
#: journey's actual order).
STAGES = (
    "queue_wait", "encode", "pack", "feed_staging", "h2d",
    "dispatch", "fetch", "commit", "publish_lag",
)


class BatchTrace:
    """One batch's reconstructed record."""

    __slots__ = (
        "batch_id", "assemble_ts", "members", "enqueues", "stage_us",
        "commit_end", "publish_ts", "publish_version", "mode",
    )

    def __init__(self, batch_id: str, assemble_ts: float,
                 members: list, enqueues: list) -> None:
        self.batch_id = batch_id
        self.assemble_ts = assemble_ts
        self.members = members
        self.enqueues = enqueues
        self.stage_us: dict[str, float] = {}
        self.commit_end: float | None = None
        self.publish_ts: float | None = None
        self.publish_version: int | None = None
        self.mode: str | None = None


class TraceModel:
    """The joined view over one trace export."""

    def __init__(self) -> None:
        self.batches: dict[str, BatchTrace] = {}
        self.match_batch: dict[str, str] = {}
        self.enqueue_ts: dict[str, float] = {}

    def batch_of(self, match_id: str) -> BatchTrace | None:
        bid = self.match_batch.get(match_id)
        return self.batches.get(bid) if bid else None


def load_events(path: str) -> list[dict]:
    """Parses a trace-events JSONL file — or, given a flight-recorder
    dump directory, its ``trace.jsonl``. Raises OSError/ValueError on
    unreadable or malformed input (a truncated final line is tolerated:
    a crashed run must still analyze)."""
    if os.path.isdir(path):
        path = os.path.join(path, "trace.jsonl")
    events: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                # Only the final line may be torn (crash mid-write).
                remainder = f.read().strip()
                if remainder:
                    raise ValueError(
                        f"{path}:{i + 1}: malformed trace event"
                    ) from None
    return events


def build_model(events: list[dict]) -> TraceModel:
    """Joins raw trace events into a :class:`TraceModel`. Events from
    untraced work (no causal ids — warmup, other runs sharing the ring)
    are skipped; a bounded ring that dropped a batch's early events
    yields a partial record, which :func:`verify_chain` reports instead
    of hiding."""
    model = TraceModel()
    # The ring appends in emission order per thread but interleaves
    # across threads; ts-sorting makes the join order-insensitive.
    for ev in sorted(events, key=lambda e: e.get("ts", 0.0)):
        name = ev.get("name")
        args = ev.get("args") or {}
        ts = float(ev.get("ts", 0.0))
        if name == "trace.enqueue":
            trace = args.get("trace")
            if trace is not None:
                model.enqueue_ts.setdefault(str(trace), ts)
            continue
        if name == "batch.assemble":
            bid = args.get("batch")
            if bid is None:
                continue
            members = [str(m) for m in (args.get("members") or [])]
            bt = BatchTrace(
                str(bid), ts, members, list(args.get("enqueues") or [])
            )
            model.batches[bt.batch_id] = bt
            for m in members:
                model.match_batch[m] = bt.batch_id
            continue
        trace = args.get("trace")
        if trace is None or str(trace) not in model.batches:
            continue
        bt = model.batches[str(trace)]
        if name == "view.publish":
            if bt.publish_ts is None:  # first publish wins: the moment
                bt.publish_ts = ts     # the rows became serve-visible
                bt.publish_version = args.get("version")
            continue
        if ev.get("ph") != "X":
            continue
        if name == "batch.lifecycle":
            bt.mode = args.get("mode")
            continue
        stage = STAGE_OF.get(name)
        if stage is None:
            continue
        dur = float(ev.get("dur", 0.0))
        bt.stage_us[stage] = bt.stage_us.get(stage, 0.0) + dur
        if stage == "commit":
            end = ts + dur
            if bt.commit_end is None or end > bt.commit_end:
                bt.commit_end = end
    return model


def _ms(us: float | None) -> float | None:
    return None if us is None else round(us / 1e3, 3)


def batch_report(bt: BatchTrace) -> dict:
    """One batch's stage decomposition, milliseconds."""
    waits = [
        bt.assemble_ts - e
        for e in bt.enqueues
        if isinstance(e, (int, float))
    ]
    stages: dict[str, float | None] = {
        "queue_wait": _ms(max(waits)) if waits else None,
    }
    for s in STAGES[1:-1]:
        stages[s] = _ms(bt.stage_us.get(s))
    stages["publish_lag"] = (
        _ms(bt.publish_ts - bt.commit_end)
        if bt.publish_ts is not None and bt.commit_end is not None
        else None
    )
    return {
        "batch": bt.batch_id,
        "mode": bt.mode,
        "matches": len(bt.members),
        "assemble_us": round(bt.assemble_ts, 1),
        "stages_ms": stages,
        "publish_version": bt.publish_version,
        "end_to_end_ms": (
            _ms(bt.publish_ts - min(
                [e for e in bt.enqueues if isinstance(e, (int, float))],
                default=bt.assemble_ts,
            ))
            if bt.publish_ts is not None else None
        ),
    }


def match_report(model: TraceModel, match_id: str) -> dict | None:
    """One match's journey: its own queue wait plus its batch's stage
    decomposition. None when the trace never saw the match."""
    bt = model.batch_of(match_id)
    enq = model.enqueue_ts.get(match_id)
    if enq is None and bt is not None and match_id in bt.members:
        e = bt.enqueues[bt.members.index(match_id)]
        enq = float(e) if isinstance(e, (int, float)) else None
    if bt is None and enq is None:
        return None
    report = {
        "match": match_id,
        "enqueue_us": None if enq is None else round(enq, 1),
        "batch": None,
        "queue_wait_ms": None,
        "stages_ms": None,
        "publish_version": None,
        "end_to_end_ms": None,
    }
    if bt is None:
        return report
    b = batch_report(bt)
    report["batch"] = bt.batch_id
    report["queue_wait_ms"] = (
        _ms(bt.assemble_ts - enq) if enq is not None else None
    )
    stages = dict(b["stages_ms"])
    stages["queue_wait"] = report["queue_wait_ms"]
    report["stages_ms"] = stages
    report["publish_version"] = bt.publish_version
    if bt.publish_ts is not None and enq is not None:
        report["end_to_end_ms"] = _ms(bt.publish_ts - enq)
    return report


def verify_chain(model: TraceModel, match_id: str) -> list[str]:
    """The completeness/monotonicity check the e2e tests gate on:
    returns human-readable problems (empty = the chain enqueue ->
    batch -> commit -> publish reconstructs completely with monotone
    timestamps)."""
    problems: list[str] = []
    bt = model.batch_of(match_id)
    if bt is None:
        return [f"{match_id}: no batch.assemble names this match"]
    enq = model.enqueue_ts.get(match_id)
    if enq is None and match_id in bt.members:
        e = bt.enqueues[bt.members.index(match_id)]
        enq = float(e) if isinstance(e, (int, float)) else None
    if enq is None:
        problems.append(f"{match_id}: no enqueue timestamp")
    for stage in ("encode", "dispatch", "commit"):
        if not bt.stage_us.get(stage):
            problems.append(
                f"{match_id}: batch {bt.batch_id} has no {stage} span"
            )
    if bt.publish_ts is None or bt.publish_version is None:
        problems.append(
            f"{match_id}: batch {bt.batch_id} never published a view "
            "version"
        )
    # Monotone timeline (us, one tracer epoch): enqueue <= assemble;
    # commit ends before the publish that exposes it.
    if enq is not None and enq > bt.assemble_ts + 1.0:
        problems.append(
            f"{match_id}: enqueue ({enq:.1f}) after batch assembly "
            f"({bt.assemble_ts:.1f})"
        )
    if (
        bt.publish_ts is not None
        and bt.commit_end is not None
        and bt.commit_end > bt.publish_ts + 1.0
    ):
        problems.append(
            f"{match_id}: commit end ({bt.commit_end:.1f}) after view "
            f"publish ({bt.publish_ts:.1f})"
        )
    if enq is not None and bt.publish_ts is not None and (
        enq > bt.publish_ts
    ):
        problems.append(
            f"{match_id}: enqueue after the publish that served it"
        )
    return problems


def critical_path(model: TraceModel, window: int | None = None) -> dict:
    """Aggregate stage decomposition over a window of batches (the last
    ``window`` by assembly time; None = all): total ms and share per
    stage, and the DOMINANT stage — what a staleness/p99 page should
    look at first. Queue wait and publish lag aggregate per batch
    (max-wait member and commit->publish gap respectively)."""
    batches = sorted(model.batches.values(), key=lambda b: b.assemble_ts)
    if window:
        batches = batches[-window:]
    totals = {s: 0.0 for s in STAGES}
    counted = {s: 0 for s in STAGES}
    matches = 0
    for bt in batches:
        matches += len(bt.members)
        rep = batch_report(bt)["stages_ms"]
        for s in STAGES:
            v = rep.get(s)
            if v is not None:
                totals[s] += v
                counted[s] += 1
    grand = sum(totals.values())
    dominant = max(totals, key=lambda s: totals[s]) if grand > 0 else None
    return {
        "batches": len(batches),
        "matches": matches,
        "stages_ms": {s: round(totals[s], 3) for s in STAGES},
        "stage_share": {
            s: (round(totals[s] / grand, 4) if grand > 0 else None)
            for s in STAGES
        },
        "batches_counted": counted,
        "dominant_stage": dominant,
    }


# -- rendering (cli trace) --------------------------------------------------

def _fmt_ms(v) -> str:
    return "-" if v is None else f"{v:.3f}"


def render_stages(stages: dict, indent: str = "  ") -> str:
    width = max(len(s) for s in STAGES)
    return "\n".join(
        f"{indent}{s.ljust(width)}  {_fmt_ms(stages.get(s))} ms"
        for s in STAGES
    )


def render_match(report: dict) -> str:
    out = [f"match {report['match']}"]
    if report["batch"] is None:
        out.append("  enqueued but never assembled into a batch "
                   "(still queued, dead-lettered, or outside the ring)")
        return "\n".join(out) + "\n"
    out.append(f"  batch {report['batch']}"
               + (f" ({report.get('mode')})" if report.get("mode") else ""))
    if report["stages_ms"]:
        out.append(render_stages(report["stages_ms"]))
    v = report["publish_version"]
    out.append(
        f"  served at view v{v}" if v is not None
        else "  never became serve-visible in this trace"
    )
    if report["end_to_end_ms"] is not None:
        out.append(f"  end-to-end {report['end_to_end_ms']:.3f} ms "
                   "(enqueue -> served-visible)")
    return "\n".join(out) + "\n"


def render_batch(report: dict) -> str:
    out = [
        f"batch {report['batch']} ({report['matches']} matches"
        + (f", {report['mode']}" if report.get("mode") else "") + ")"
    ]
    out.append(render_stages(report["stages_ms"]))
    v = report["publish_version"]
    out.append(
        f"  served at view v{v}" if v is not None
        else "  never became serve-visible in this trace"
    )
    return "\n".join(out) + "\n"


def render_critical_path(cp: dict) -> str:
    out = [
        f"critical path over {cp['batches']} batch(es) / "
        f"{cp['matches']} match(es):"
    ]
    grand = sum(v for v in cp["stages_ms"].values())
    width = max(len(s) for s in STAGES)
    for s in STAGES:
        total = cp["stages_ms"][s]
        share = cp["stage_share"][s]
        pct = "" if share is None else f"  {100 * share:5.1f}%"
        out.append(f"  {s.ljust(width)}  {total:10.3f} ms{pct}")
    out.append(
        f"  dominant stage: {cp['dominant_stage']}"
        if cp["dominant_stage"] else "  (no attributable stage time)"
    )
    out.append(f"  total attributed: {grand:.3f} ms")
    return "\n".join(out) + "\n"
