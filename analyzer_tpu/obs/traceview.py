"""Trace analyzer: reconstruct per-match / per-batch timelines from a
trace-events export.

Input is the Chrome trace-event JSONL the tracer exports (``cli rate
--trace-events``, ``cli soak --trace-events``, or the ``trace.jsonl``
inside a flight-recorder dump directory). With causal tracing enabled
(obs/tracectx.py) those events carry the ids that make reconstruction
possible:

  * ``trace.enqueue`` instants anchor each match's timeline at the
    moment it entered the broker;
  * ``batch.assemble`` instants record which match traces joined which
    batch (``batch`` id + ``members`` + ``enqueues``);
  * every span the batch's pipeline emitted — encode, pack, the feed
    thread's materialize/transfer, dispatch, fetch, commit — carries
    ``args.trace`` = the batch id;
  * ``view.publish`` instants mark the version that made the batch's
    rows serve-visible.

:func:`build_model` joins those into a :class:`TraceModel`;
:func:`match_report` / :func:`batch_report` decompose one journey into
the operator-facing stages (queue wait, encode, pack, feed staging,
H2D, dispatch, fetch, commit, publish lag); :func:`critical_path`
aggregates a window of batches and names the dominant stage — the
number a staleness page actually needs. ``cli trace`` renders all
three; the soak driver embeds :func:`critical_path` into the SOAK
artifact. Stdlib-only, like the rest of the exposition layer.

**Cross-process stitching** (docs/observability.md "Fleet plane"):
trace ids already ride broker message headers across process
boundaries (obs/tracectx.py), so a match enqueued on host A and rated
on host B leaves its ``trace.enqueue`` anchor in A's export and the
rest of its chain in B's. :func:`load_forest` joins *multiple*
``--trace-events`` files / flight-dump dirs into one trace forest: each
export's leading ``trace_epoch`` metadata (the tracer's wall epoch)
rebases its microsecond timeline onto one wall-aligned axis, every
event is tagged with its source host label, and the enqueue→assemble
gap of a cross-host chain surfaces as its own ``broker_transit`` stage
(network + broker residency — queue wait measured across machines)
instead of silently inflating ``queue_wait``. :func:`critical_path`
then attributes each stage to the host whose spans produced it.
``cli trace --match M f1.jsonl f2.jsonl`` drives the whole join.
"""

from __future__ import annotations

import json
import os

#: Span name -> stage bucket of the operator-facing decomposition.
#: ``batch.compute`` / ``batch.dispatch`` are ENQUEUE cost (dispatch);
#: device time surfaces host-side in ``batch.fetch``; the tier manager's
#: promote/demote traffic is feed-thread staging work.
STAGE_OF = {
    "batch.encode": "encode",
    "batch.pack": "pack",
    "batch.chain": "dispatch",
    "batch.dispatch": "dispatch",
    "batch.compute": "dispatch",
    "feed.materialize": "feed_staging",
    "tier.promote": "feed_staging",
    "tier.demote": "feed_staging",
    "feed.transfer": "h2d",
    "batch.fetch": "fetch",
    "batch.write_back": "commit",
    "batch.commit": "commit",
}

#: Stage order for reports (queue wait first, publish lag last — the
#: journey's actual order). ``broker_transit`` is the cross-process
#: handoff gap of a STITCHED chain (enqueue on host A -> batch assembly
#: on host B, wall-aligned); single-process chains report it as None
#: and carry the same gap as ``queue_wait``.
STAGES = (
    "queue_wait", "broker_transit", "encode", "pack", "feed_staging", "h2d",
    "dispatch", "fetch", "commit", "publish_lag",
)


class BatchTrace:
    """One batch's reconstructed record."""

    __slots__ = (
        "batch_id", "assemble_ts", "members", "enqueues", "stage_us",
        "commit_end", "publish_ts", "publish_version", "mode",
        "host", "cross_host", "transit_label",
    )

    def __init__(self, batch_id: str, assemble_ts: float,
                 members: list, enqueues: list,
                 host: str | None = None) -> None:
        self.batch_id = batch_id
        self.assemble_ts = assemble_ts
        self.members = members
        self.enqueues = enqueues
        self.stage_us: dict[str, float] = {}
        self.commit_end: float | None = None
        self.publish_ts: float | None = None
        self.publish_version: int | None = None
        self.mode: str | None = None
        # Stitched-forest attribution (load_forest): which host's export
        # assembled this batch, whether any member was enqueued on a
        # DIFFERENT host (the broker_transit case), and the handoff's
        # "src->dst" label for the critical-path report.
        self.host = host
        self.cross_host = False
        self.transit_label: str | None = None


class TraceModel:
    """The joined view over one trace export (or a stitched forest)."""

    def __init__(self) -> None:
        self.batches: dict[str, BatchTrace] = {}
        self.match_batch: dict[str, str] = {}
        self.enqueue_ts: dict[str, float] = {}
        # Stitched forests only: which host's export anchored each
        # match's enqueue, and every host label seen.
        self.enqueue_host: dict[str, str] = {}
        self.hosts: set[str] = set()

    def batch_of(self, match_id: str) -> BatchTrace | None:
        bid = self.match_batch.get(match_id)
        return self.batches.get(bid) if bid else None


def load_events(path: str, host: str | None = None) -> list[dict]:
    """Parses a trace-events JSONL file — or, given a flight-recorder
    dump directory, its ``trace.jsonl``. ``host`` tags every event with
    a source label (the stitcher's attribution key). Raises
    OSError/ValueError on unreadable or malformed input (a truncated
    final line is tolerated: a crashed run must still analyze)."""
    if os.path.isdir(path):
        path = os.path.join(path, "trace.jsonl")
    events: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except ValueError:
                # Only the final line may be torn (crash mid-write).
                remainder = f.read().strip()
                if remainder:
                    raise ValueError(
                        f"{path}:{i + 1}: malformed trace event"
                    ) from None
                continue
            if host is not None:
                event["_host"] = host
            events.append(event)
    return events


def host_label(path: str) -> str:
    """A human host label for one trace source: the flight-dump
    directory name, or the file's basename minus extension."""
    path = path.rstrip("/\\")
    base = os.path.basename(path)
    if base == "trace.jsonl":  # inside a flight dump: the dir names it
        base = os.path.basename(os.path.dirname(path)) or base
    return base.rsplit(".", 1)[0] if base.endswith(".jsonl") else base


def _file_epoch(events: list[dict]) -> float | None:
    """The export's ``trace_epoch`` metadata (tracer wall epoch)."""
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "trace_epoch":
            epoch = (ev.get("args") or {}).get("epoch_wall")
            if epoch is not None:
                return float(epoch)
    return None


def load_forest(paths: list, hosts: list | None = None) -> list[dict]:
    """Joins MULTIPLE trace exports (files or flight-dump dirs) into one
    event list on a single wall-aligned timeline: each file's events are
    rebased by its ``trace_epoch`` metadata (offsets in microseconds
    from the earliest epoch) and tagged with a host label, so
    :func:`build_model` reconstructs chains that CROSS process
    boundaries — the enqueue anchor from the publisher's export joins
    the batch spans from the worker's. Every file must carry the epoch
    metadata (exports since the stitcher landed do); a file without it
    cannot be clock-aligned and fails loudly."""
    if hosts is None:
        hosts = []
        for p in paths:
            label = host_label(p)
            while label in hosts:  # two files, one basename: suffix
                label += "'"
            hosts.append(label)
    per_file = []
    for path, host in zip(paths, hosts):
        events = load_events(path, host=host)
        epoch = _file_epoch(events)
        if epoch is None and len(paths) > 1:
            raise ValueError(
                f"{path}: no trace_epoch metadata — this export cannot "
                "be clock-aligned with the others (re-capture it, or "
                "analyze the files singly)"
            )
        per_file.append((events, epoch or 0.0))
    base = min(epoch for _, epoch in per_file)
    out: list[dict] = []
    for events, epoch in per_file:
        offset_us = (epoch - base) * 1e6
        for ev in events:
            if ev.get("ph") == "M":
                continue
            if offset_us:
                ev = dict(ev, ts=float(ev.get("ts", 0.0)) + offset_us)
            out.append(ev)
    return out


def build_model(events: list[dict]) -> TraceModel:
    """Joins raw trace events into a :class:`TraceModel`. Events from
    untraced work (no causal ids — warmup, other runs sharing the ring)
    are skipped; a bounded ring that dropped a batch's early events
    yields a partial record, which :func:`verify_chain` reports instead
    of hiding."""
    model = TraceModel()
    # The ring appends in emission order per thread but interleaves
    # across threads; ts-sorting makes the join order-insensitive.
    for ev in sorted(events, key=lambda e: e.get("ts", 0.0)):
        name = ev.get("name")
        args = ev.get("args") or {}
        ts = float(ev.get("ts", 0.0))
        host = ev.get("_host")
        if host is not None:
            model.hosts.add(host)
        if name == "trace.enqueue":
            trace = args.get("trace")
            if trace is not None:
                model.enqueue_ts.setdefault(str(trace), ts)
                if host is not None:
                    model.enqueue_host.setdefault(str(trace), host)
            continue
        # Batch trace ids (``b<N>``) come from a PROCESS-local counter —
        # two stitched exports legitimately both carry a "b1". Namespace
        # them by the event's host so the forest keeps both; every span
        # referencing a batch id lives in the same export (same host),
        # so the mapping is consistent per file. Single-export models
        # (host None) keep the raw ids, unchanged.
        if name == "batch.assemble":
            bid = args.get("batch")
            if bid is None:
                continue
            bid = f"{host}:{bid}" if host is not None else str(bid)
            members = [str(m) for m in (args.get("members") or [])]
            bt = BatchTrace(
                bid, ts, members, list(args.get("enqueues") or []),
                host=host,
            )
            model.batches[bt.batch_id] = bt
            for m in members:
                model.match_batch[m] = bt.batch_id
            continue
        trace = args.get("trace")
        if trace is None:
            continue
        trace = f"{host}:{trace}" if host is not None else str(trace)
        if trace not in model.batches:
            continue
        bt = model.batches[trace]
        if name == "view.publish":
            if bt.publish_ts is None:  # first publish wins: the moment
                bt.publish_ts = ts     # the rows became serve-visible
                bt.publish_version = args.get("version")
            continue
        if ev.get("ph") != "X":
            continue
        if name == "batch.lifecycle":
            bt.mode = args.get("mode")
            continue
        stage = STAGE_OF.get(name)
        if stage is None:
            continue
        dur = float(ev.get("dur", 0.0))
        bt.stage_us[stage] = bt.stage_us.get(stage, 0.0) + dur
        if stage == "commit":
            end = ts + dur
            if bt.commit_end is None or end > bt.commit_end:
                bt.commit_end = end
    _finalize_cross_host(model)
    return model


def _finalize_cross_host(model: TraceModel) -> None:
    """Marks batches whose members were enqueued on a DIFFERENT host
    than the one that assembled them (stitched forests only), and
    rebinds their ``enqueues`` to the publisher-side wall-aligned
    anchors — the header-borne stamps a cross-host worker recorded are
    on the PUBLISHER's unrebased timeline, so only the anchors from the
    publisher's own export can be subtracted against this batch's
    timestamps. The handoff gap then reports as ``broker_transit``."""
    for bt in model.batches.values():
        if bt.host is None:
            continue
        member_hosts = [model.enqueue_host.get(m) for m in bt.members]
        if not any(h is not None and h != bt.host for h in member_hosts):
            continue
        bt.cross_host = True
        bt.enqueues = [model.enqueue_ts.get(m) for m in bt.members]
        src = next(
            h for h in member_hosts if h is not None and h != bt.host
        )
        bt.transit_label = f"{src}->{bt.host}"


def _ms(us: float | None) -> float | None:
    return None if us is None else round(us / 1e3, 3)


def batch_report(bt: BatchTrace) -> dict:
    """One batch's stage decomposition, milliseconds. A cross-host
    batch (stitched forest) reports its enqueue->assemble gap as
    ``broker_transit`` — the handoff crossed a process/machine boundary
    — where a same-process batch reports ``queue_wait``."""
    waits = [
        bt.assemble_ts - e
        for e in bt.enqueues
        if isinstance(e, (int, float))
    ]
    gap = _ms(max(waits)) if waits else None
    stages: dict[str, float | None] = {
        "queue_wait": None if bt.cross_host else gap,
        "broker_transit": gap if bt.cross_host else None,
    }
    for s in STAGES[2:-1]:
        stages[s] = _ms(bt.stage_us.get(s))
    stages["publish_lag"] = (
        _ms(bt.publish_ts - bt.commit_end)
        if bt.publish_ts is not None and bt.commit_end is not None
        else None
    )
    report = {
        "batch": bt.batch_id,
        "mode": bt.mode,
        "matches": len(bt.members),
        "assemble_us": round(bt.assemble_ts, 1),
        "stages_ms": stages,
        "publish_version": bt.publish_version,
        "end_to_end_ms": (
            _ms(bt.publish_ts - min(
                [e for e in bt.enqueues if isinstance(e, (int, float))],
                default=bt.assemble_ts,
            ))
            if bt.publish_ts is not None else None
        ),
    }
    if bt.host is not None:
        report["host"] = bt.host
    return report


def match_report(model: TraceModel, match_id: str) -> dict | None:
    """One match's journey: its own queue wait plus its batch's stage
    decomposition. None when the trace never saw the match."""
    bt = model.batch_of(match_id)
    enq = model.enqueue_ts.get(match_id)
    if enq is None and bt is not None and match_id in bt.members:
        e = bt.enqueues[bt.members.index(match_id)]
        enq = float(e) if isinstance(e, (int, float)) else None
    if bt is None and enq is None:
        return None
    report = {
        "match": match_id,
        "enqueue_us": None if enq is None else round(enq, 1),
        "batch": None,
        "queue_wait_ms": None,
        "stages_ms": None,
        "publish_version": None,
        "end_to_end_ms": None,
    }
    if bt is None:
        return report
    b = batch_report(bt)
    report["batch"] = bt.batch_id
    gap = _ms(bt.assemble_ts - enq) if enq is not None else None
    report["queue_wait_ms"] = None if bt.cross_host else gap
    stages = dict(b["stages_ms"])
    if bt.cross_host:
        # The stitched handoff: this match left host A's broker publish
        # and surfaced in host B's batch — network + broker residency.
        stages["queue_wait"] = None
        stages["broker_transit"] = gap
        report["broker_transit_ms"] = gap
        report["enqueue_host"] = model.enqueue_host.get(match_id)
        report["batch_host"] = bt.host
    else:
        stages["queue_wait"] = gap
    report["stages_ms"] = stages
    report["publish_version"] = bt.publish_version
    if bt.publish_ts is not None and enq is not None:
        report["end_to_end_ms"] = _ms(bt.publish_ts - enq)
    return report


def verify_chain(model: TraceModel, match_id: str) -> list[str]:
    """The completeness/monotonicity check the e2e tests gate on:
    returns human-readable problems (empty = the chain enqueue ->
    batch -> commit -> publish reconstructs completely with monotone
    timestamps)."""
    problems: list[str] = []
    bt = model.batch_of(match_id)
    if bt is None:
        return [f"{match_id}: no batch.assemble names this match"]
    enq = model.enqueue_ts.get(match_id)
    if enq is None and match_id in bt.members:
        e = bt.enqueues[bt.members.index(match_id)]
        enq = float(e) if isinstance(e, (int, float)) else None
    if enq is None:
        problems.append(
            f"{match_id}: no cross-host enqueue anchor — stitch the "
            "publishing host's trace export into the forest"
            if bt.cross_host else
            f"{match_id}: no enqueue timestamp"
        )
    if bt.cross_host and enq is not None:
        # The handoff gap is its own stage on a stitched chain: the
        # wall-aligned enqueue must precede assembly (a negative
        # broker_transit means the two exports' clocks disagree).
        transit_us = bt.assemble_ts - enq
        if transit_us < -1.0:
            problems.append(
                f"{match_id}: negative broker_transit "
                f"({transit_us:.1f} us) — enqueue on "
                f"{model.enqueue_host.get(match_id)} is AFTER assembly "
                f"on {bt.host}; the exports' clocks are not aligned"
            )
    for stage in ("encode", "dispatch", "commit"):
        if not bt.stage_us.get(stage):
            problems.append(
                f"{match_id}: batch {bt.batch_id} has no {stage} span"
            )
    if bt.publish_ts is None or bt.publish_version is None:
        problems.append(
            f"{match_id}: batch {bt.batch_id} never published a view "
            "version"
        )
    # Monotone timeline (us, one tracer epoch): enqueue <= assemble;
    # commit ends before the publish that exposes it.
    if enq is not None and enq > bt.assemble_ts + 1.0:
        problems.append(
            f"{match_id}: enqueue ({enq:.1f}) after batch assembly "
            f"({bt.assemble_ts:.1f})"
        )
    if (
        bt.publish_ts is not None
        and bt.commit_end is not None
        and bt.commit_end > bt.publish_ts + 1.0
    ):
        problems.append(
            f"{match_id}: commit end ({bt.commit_end:.1f}) after view "
            f"publish ({bt.publish_ts:.1f})"
        )
    if enq is not None and bt.publish_ts is not None and (
        enq > bt.publish_ts
    ):
        problems.append(
            f"{match_id}: enqueue after the publish that served it"
        )
    return problems


def critical_path(model: TraceModel, window: int | None = None) -> dict:
    """Aggregate stage decomposition over a window of batches (the last
    ``window`` by assembly time; None = all): total ms and share per
    stage, and the DOMINANT stage — what a staleness/p99 page should
    look at first. Queue wait and publish lag aggregate per batch
    (max-wait member and commit->publish gap respectively)."""
    batches = sorted(model.batches.values(), key=lambda b: b.assemble_ts)
    if window:
        batches = batches[-window:]
    totals = {s: 0.0 for s in STAGES}
    counted = {s: 0 for s in STAGES}
    stage_hosts: dict[str, dict[str, float]] = {s: {} for s in STAGES}
    matches = 0
    for bt in batches:
        matches += len(bt.members)
        rep = batch_report(bt)["stages_ms"]
        for s in STAGES:
            v = rep.get(s)
            if v is not None:
                totals[s] += v
                counted[s] += 1
                if bt.host is not None:
                    # Span stages ran on the assembling host; the
                    # handoff belongs to the src->dst pair.
                    owner = (
                        bt.transit_label
                        if s == "broker_transit" and bt.transit_label
                        else bt.host
                    )
                    hosts = stage_hosts[s]
                    hosts[owner] = hosts.get(owner, 0.0) + v
    grand = sum(totals.values())
    dominant = max(totals, key=lambda s: totals[s]) if grand > 0 else None
    out = {
        "batches": len(batches),
        "matches": matches,
        "stages_ms": {s: round(totals[s], 3) for s in STAGES},
        "stage_share": {
            s: (round(totals[s] / grand, 4) if grand > 0 else None)
            for s in STAGES
        },
        "batches_counted": counted,
        "dominant_stage": dominant,
    }
    if model.hosts:
        # Stitched forests attribute each stage to its host (the fleet
        # question: WHICH machine owns the dominant stage). Absent on
        # single-export models so existing artifacts are unchanged.
        out["hosts"] = sorted(model.hosts)
        out["stage_hosts"] = {
            s: {h: round(v, 3) for h, v in sorted(hosts.items())}
            for s, hosts in stage_hosts.items() if hosts
        }
        if dominant is not None and stage_hosts.get(dominant):
            out["dominant_host"] = max(
                stage_hosts[dominant], key=stage_hosts[dominant].get
            )
    return out


# -- rendering (cli trace) --------------------------------------------------

def _fmt_ms(v) -> str:
    return "-" if v is None else f"{v:.3f}"


def render_stages(stages: dict, indent: str = "  ") -> str:
    width = max(len(s) for s in STAGES)
    return "\n".join(
        f"{indent}{s.ljust(width)}  {_fmt_ms(stages.get(s))} ms"
        for s in STAGES
    )


def render_match(report: dict) -> str:
    out = [f"match {report['match']}"]
    if report["batch"] is None:
        out.append("  enqueued but never assembled into a batch "
                   "(still queued, dead-lettered, or outside the ring)")
        return "\n".join(out) + "\n"
    out.append(f"  batch {report['batch']}"
               + (f" ({report.get('mode')})" if report.get("mode") else ""))
    if report.get("enqueue_host") or report.get("batch_host"):
        out.append(
            f"  cross-host: enqueued on {report.get('enqueue_host') or '?'}"
            f", rated on {report.get('batch_host') or '?'}"
        )
    if report["stages_ms"]:
        out.append(render_stages(report["stages_ms"]))
    v = report["publish_version"]
    out.append(
        f"  served at view v{v}" if v is not None
        else "  never became serve-visible in this trace"
    )
    if report["end_to_end_ms"] is not None:
        out.append(f"  end-to-end {report['end_to_end_ms']:.3f} ms "
                   "(enqueue -> served-visible)")
    return "\n".join(out) + "\n"


def render_batch(report: dict) -> str:
    out = [
        f"batch {report['batch']} ({report['matches']} matches"
        + (f", {report['mode']}" if report.get("mode") else "") + ")"
    ]
    out.append(render_stages(report["stages_ms"]))
    v = report["publish_version"]
    out.append(
        f"  served at view v{v}" if v is not None
        else "  never became serve-visible in this trace"
    )
    return "\n".join(out) + "\n"


def render_critical_path(cp: dict) -> str:
    out = [
        f"critical path over {cp['batches']} batch(es) / "
        f"{cp['matches']} match(es)"
        + (f" across hosts {', '.join(cp['hosts'])}" if cp.get("hosts")
           else "") + ":"
    ]
    grand = sum(v for v in cp["stages_ms"].values())
    width = max(len(s) for s in STAGES)
    stage_hosts = cp.get("stage_hosts") or {}
    for s in STAGES:
        total = cp["stages_ms"][s]
        share = cp["stage_share"][s]
        pct = "" if share is None else f"  {100 * share:5.1f}%"
        hosts = stage_hosts.get(s)
        attribution = ""
        if hosts:
            attribution = "  [" + ", ".join(
                f"{h} {v:.3f}" for h, v in hosts.items()
            ) + "]"
        out.append(f"  {s.ljust(width)}  {total:10.3f} ms{pct}{attribution}")
    out.append(
        f"  dominant stage: {cp['dominant_stage']}"
        + (f" (on {cp['dominant_host']})" if cp.get("dominant_host") else "")
        if cp["dominant_stage"] else "  (no attributable stage time)"
    )
    out.append(f"  total attributed: {grand:.3f} ms")
    return "\n".join(out) + "\n"
