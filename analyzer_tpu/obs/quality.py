"""Rating-quality observability: the online calibration ledger.

Six planes watch the system's *speed*; this one watches whether the
ratings are any *good* (ROADMAP item 4(c)). The ledger scores every
rated match's **pre-update** predicted win probability — the exact
serve-plane Phi link (:func:`analyzer_tpu.serve.oracle.win_probability`,
the sigma-inclusive form the read plane actually serves) over the
batch's prior ratings — against the realized outcome, and accumulates:

  * binned reliability counts (``quality.bin_count{bin=}`` /
    ``quality.bin_p_sum{bin=}`` / ``quality.bin_y_sum{bin=}``) plus
    streaming Brier score and log-loss, mirrored into ``quality.*``
    registry COUNTERS — counters sum, so fleet federation
    (obs/federate.py) and the history rings (obs/history.py) work for
    free, and the live ``calibration-floor`` objective (obs/slo.py)
    computes an exact windowed ECE from ring deltas;
  * population-drift telemetry: a mu-distribution PSI against a pinned
    reference window, and sigma convergence by games-played cohort —
    the "is the system still converging" signal;
  * a bounded prefix of (logit, outcome) pairs for temperature fitting
    (``cli quality --fit-temperature`` via models/calibration.py).

CLOCK-INJECTED and deterministic (graftlint GL047): every timestamp is
passed in by the caller (the worker's clock — the soak's VirtualClock),
and every bin edge / threshold literal lives in the ONE declared table
below (:data:`QUALITY_TABLE`), so the soak's ``quality`` block is
byte-identical per (seed, config) and the thresholds have one home.

Consumers: the worker's commit site (service/worker.py), ``/qualityz``
(obs/server.py), ``cli quality``, the soak artifact's ``quality`` block
(loadgen/driver.py), benchdiff's soak family, and ``cli migrate``'s
staging-vs-live replay judge (:func:`score_table`).
"""

from __future__ import annotations

import math
import threading

import numpy as np

#: The module's ONE table of bin edges and thresholds (graftlint GL047
#: confines numeric threshold literals in this module to this span —
#: a pasted magic number elsewhere silently forks the calibration
#: verdict every consumer is judged against).
QUALITY_TABLE = {
    # Reliability diagram: equal-width bins over predicted P(A wins).
    "bins": 10,
    # Probability clamp for log-loss and retained logits (matches the
    # spirit of models/calibration.py's own nll epsilon).
    "prob_eps": 1e-6,
    # Retained (logit, outcome) prefix for temperature fitting.
    "retain_max": 4096,
    # Population-drift PSI: histogram bins over the pinned reference's
    # mu range, smoothing epsilon, and the classic 0.25 alert floor.
    "psi_bins": 10,
    "psi_eps": 1e-4,
    "psi_alert": 0.25,
    # ECE alert floor — the calibration-floor objective's default
    # threshold (obs/slo.py STANDARD_OBJECTIVES reads the same number).
    "ece_alert": 0.25,
    # Minimum scored matches before any verdict (volume guard — low
    # enough that the default smoke soak's window is judged).
    "min_matches": 128,
    # Games-played cohort edges for sigma-convergence telemetry:
    # cohorts are [0, e0), [e0, e1), [e1, e2), [e2, inf).
    "cohort_edges": (5, 10, 20),
}


def _logit(p: float) -> float:
    eps = QUALITY_TABLE["prob_eps"]
    p = min(max(p, eps), 1.0 - eps)
    return math.log(p / (1.0 - p))


def ece_from_bins(p_sum, y_sum, total: float) -> float | None:
    """Expected calibration error from binned sums: the count-weighted
    mean |mean_p - mean_y| gap, which reduces to
    ``sum_b |p_sum_b - y_sum_b| / total``. This identity is what lets
    the live objective compute an EXACT windowed ECE from history-ring
    counter deltas (obs/slo.py ``calibration`` kind) — no extra state,
    and the same formula federates across hosts because counters sum."""
    if total <= 0:
        return None
    gap = 0.0
    for ps, ys in zip(p_sum, y_sum):
        gap += abs(float(ps) - float(ys))
    return gap / float(total)


class CalibrationLedger:
    """Streaming reliability/drift accounting for one worker.

    Single-writer (the worker's consume thread scores batches), multi-
    reader (``/qualityz`` and ``stats()`` snapshot under the lock).
    ``mirror=False`` (the replay judge) skips registry side effects so
    :func:`score_table` stays a pure function of its inputs.
    """

    def __init__(self, cfg, mirror: bool = True) -> None:
        self.cfg = cfg
        self._beta2 = float(cfg.beta2)
        self._mirror = mirror
        self._lock = threading.Lock()
        bins = int(QUALITY_TABLE["bins"])
        self._bins = bins
        self._bin_count = np.zeros(bins, dtype=np.int64)
        self._bin_p_sum = np.zeros(bins, dtype=np.float64)
        self._bin_y_sum = np.zeros(bins, dtype=np.float64)
        self._n = 0
        self._brier_sum = 0.0
        self._logloss_sum = 0.0
        # Bounded first-N retention for temperature fitting: the prefix
        # is deterministic per stream (no sampling RNG to seed).
        self._z: list[float] = []
        self._y: list[float] = []
        # The ledger's own games-played counts (rows -> rated matches
        # scored), feeding the sigma-convergence cohorts.
        self._games: dict[int, int] = {}
        # Population drift: reference histogram pinned at the first
        # observed window; latest snapshot kept for reporting.
        self._ref_edges: np.ndarray | None = None
        self._ref_frac: np.ndarray | None = None
        self._drift: dict | None = None

    # -- scoring ----------------------------------------------------------
    def score_batch(
        self, table, player_idx, winner, mode_id, afk, pad_row: int
    ) -> int:
        """Scores one committed batch against its PRE-update priors.

        ``table`` is a host ``[R, 16]`` prior snapshot (full table or a
        compact row gather — ``player_idx`` must index it), the stream
        arrays are host views of the batch's MatchStream. Only ratable
        matches (supported mode, no AFK) score — the same gate the
        rating kernel applies. Returns the number scored."""
        from analyzer_tpu.serve.oracle import win_probability

        table = np.asarray(table)
        player_idx = np.asarray(player_idx)
        winner = np.asarray(winner)
        mode_id = np.asarray(mode_id)
        afk = np.asarray(afk)
        n_scored = 0
        bins = self._bins
        d_count = np.zeros(bins, dtype=np.int64)
        d_p = np.zeros(bins, dtype=np.float64)
        d_y = np.zeros(bins, dtype=np.float64)
        d_brier = 0.0
        d_logloss = 0.0
        eps = QUALITY_TABLE["prob_eps"]
        retain_max = int(QUALITY_TABLE["retain_max"])
        pairs: list[tuple[float, float]] = []
        games: list[int] = []
        for b in range(player_idx.shape[0]):
            if int(mode_id[b]) < 0 or bool(afk[b]):
                continue
            # Empty slots are -1 in a raw MatchStream and pad_row in a
            # packed schedule — both drop from the team reduction.
            rows_a = [
                int(r) for r in player_idx[b, 0]
                if int(r) >= 0 and int(r) != pad_row
            ]
            rows_b = [
                int(r) for r in player_idx[b, 1]
                if int(r) >= 0 and int(r) != pad_row
            ]
            if not rows_a or not rows_b:
                continue
            p = float(win_probability(table, rows_a, rows_b, self._beta2))
            y = 1.0 if int(winner[b]) == 0 else 0.0
            k = min(int(p * bins), bins - 1)
            d_count[k] += 1
            d_p[k] += p
            d_y[k] += y
            d_brier += (p - y) * (p - y)
            pc = min(max(p, eps), 1.0 - eps)
            d_logloss += -(y * math.log(pc) + (1.0 - y) * math.log(1.0 - pc))
            pairs.append((_logit(p), y))
            games.extend(rows_a)
            games.extend(rows_b)
            n_scored += 1
        if not n_scored:
            return 0
        with self._lock:
            self._bin_count += d_count
            self._bin_p_sum += d_p
            self._bin_y_sum += d_y
            self._n += n_scored
            self._brier_sum += d_brier
            self._logloss_sum += d_logloss
            for z, y in pairs:
                if len(self._z) >= retain_max:
                    break
                self._z.append(z)
                self._y.append(y)
            for row in games:
                self._games[row] = self._games.get(row, 0) + 1
        if self._mirror:
            self._mirror_scores(d_count, d_p, d_y, d_brier, d_logloss)
        return n_scored

    def _mirror_scores(self, d_count, d_p, d_y, d_brier, d_logloss) -> None:
        """Pushes one batch's deltas into the ``quality.*`` registry
        series. Counters only for the accumulating state (they sum —
        fleet merge + ring deltas stay exact); the derived running
        means ride as gauges for human scrape pages."""
        from analyzer_tpu.obs.registry import get_registry

        reg = get_registry()
        reg.counter("quality.matches_scored_total").add(float(d_count.sum()))
        reg.counter("quality.brier_sum").add(d_brier)
        reg.counter("quality.logloss_sum").add(d_logloss)
        for k in range(self._bins):
            if not d_count[k]:
                continue
            reg.counter("quality.bin_count", bin=k).add(float(d_count[k]))
            reg.counter("quality.bin_p_sum", bin=k).add(float(d_p[k]))
            reg.counter("quality.bin_y_sum", bin=k).add(float(d_y[k]))
        with self._lock:
            n = self._n
            brier = self._brier_sum / n if n else None
            ece = ece_from_bins(self._bin_p_sum, self._bin_y_sum, n)
        reg.gauge("quality.brier").set(
            round(brier, 6) if brier is not None else None
        )
        reg.gauge("quality.ece").set(
            round(ece, 6) if ece is not None else None
        )

    # -- population drift -------------------------------------------------
    def observe_population(self, table, now: float | None = None) -> None:
        """One drift snapshot over a committed HOST table (the served
        view's ``host_table()``): pins the reference mu histogram on the
        first call with enough rated rows, then tracks PSI against it,
        plus per-cohort mean sigma (cohorts from the ledger's own
        games-played counts). ``now`` comes from the CALLER's clock
        (GL047 — this module never owns one)."""
        from analyzer_tpu.core.state import MU_LO, SIGMA_LO

        table = np.asarray(table)
        mu = np.asarray(table[:, MU_LO], dtype=np.float64)
        sigma = np.asarray(table[:, SIGMA_LO], dtype=np.float64)
        rated = ~np.isnan(mu)
        n_rated = int(rated.sum())
        psi_bins = int(QUALITY_TABLE["psi_bins"])
        eps = float(QUALITY_TABLE["psi_eps"])
        with self._lock:
            if self._ref_edges is None:
                if n_rated < psi_bins:
                    return
                lo = float(mu[rated].min())
                hi = float(mu[rated].max())
                if hi <= lo:
                    hi = lo + 1.0
                self._ref_edges = np.linspace(lo, hi, psi_bins + 1)
                self._ref_frac = self._mu_fractions(mu[rated], eps)
                psi = 0.0
            else:
                if not n_rated:
                    return
                cur = self._mu_fractions(mu[rated], eps)
                psi = float(
                    np.sum((cur - self._ref_frac) * np.log(cur / self._ref_frac))
                )
            cohorts = self._sigma_cohorts(sigma, rated)
            self._drift = {
                "t": round(float(now), 6) if now is not None else None,
                "rated_rows": n_rated,
                "psi_mu": round(psi, 6),
                "psi_alert": psi >= float(QUALITY_TABLE["psi_alert"]),
                "sigma_by_cohort": cohorts,
            }
        if self._mirror:
            from analyzer_tpu.obs.registry import get_registry

            get_registry().gauge("quality.psi_mu").set(round(psi, 6))

    def _mu_fractions(self, mu_rated: np.ndarray, eps: float) -> np.ndarray:
        """Smoothed per-bin fractions of rated mu over the PINNED
        reference edges (outer rows clip into the edge bins, so a
        drifting population registers instead of escaping the range)."""
        edges = self._ref_edges
        idx = np.clip(
            np.searchsorted(edges, mu_rated, side="right") - 1,
            0, len(edges) - 2,
        )
        counts = np.bincount(idx, minlength=len(edges) - 1).astype(np.float64)
        frac = counts / counts.sum()
        frac = frac + eps
        return frac / frac.sum()

    def _sigma_cohorts(self, sigma: np.ndarray, rated: np.ndarray) -> dict:
        """Mean sigma by games-played cohort — converging populations
        show monotonically falling sigma with games played; a flat
        profile means the system stopped learning."""
        edges = QUALITY_TABLE["cohort_edges"]
        names = ["0-%d" % (edges[0] - 1)]
        names += [
            "%d-%d" % (edges[i], edges[i + 1] - 1)
            for i in range(len(edges) - 1)
        ]
        names.append("%d+" % edges[-1])
        sums = [0.0] * len(names)
        counts = [0] * len(names)
        for row, games in self._games.items():
            if row >= len(sigma) or not rated[row]:
                continue
            k = 0
            for i, e in enumerate(edges):
                if games >= e:
                    k = i + 1
            sums[k] += float(sigma[row])
            counts[k] += 1
        return {
            name: (round(sums[i] / counts[i], 4) if counts[i] else None)
            for i, name in enumerate(names)
        }

    # -- reporting --------------------------------------------------------
    def retained(self) -> tuple[np.ndarray, np.ndarray]:
        """The retained (logit, outcome) prefix for temperature fitting
        (models/calibration.py fit_temperature's inputs)."""
        with self._lock:
            return (
                np.asarray(self._z, dtype=np.float64),
                np.asarray(self._y, dtype=np.float64),
            )

    def worst_bin(self) -> dict | None:
        """The reliability bin with the largest |mean_p - mean_y| gap —
        what the SLO-burn log names when calibration-floor burns."""
        with self._lock:
            worst = None
            for k in range(self._bins):
                c = int(self._bin_count[k])
                if not c:
                    continue
                mean_p = float(self._bin_p_sum[k]) / c
                mean_y = float(self._bin_y_sum[k]) / c
                gap = abs(mean_p - mean_y)
                if worst is None or gap > worst["gap"]:
                    worst = {
                        "bin": k,
                        "lo": round(k / self._bins, 2),
                        "hi": round((k + 1) / self._bins, 2),
                        "count": c,
                        "mean_p": round(mean_p, 4),
                        "mean_y": round(mean_y, 4),
                        "gap": round(gap, 4),
                    }
            return worst

    def stats(self) -> dict:
        """The compact ``Worker.stats()['quality']`` block."""
        with self._lock:
            n = self._n
            return {
                "matches_scored": n,
                "brier": round(self._brier_sum / n, 6) if n else None,
                "ece": (
                    round(
                        ece_from_bins(self._bin_p_sum, self._bin_y_sum, n), 6
                    )
                    if n else None
                ),
                "psi_mu": (
                    self._drift["psi_mu"] if self._drift is not None else None
                ),
            }

    def summary(self) -> dict:
        """The full report: reliability table, streaming scores, drift
        snapshot, retention. Deterministic for a deterministic input
        stream (the soak artifact's ``quality`` block is this dict,
        byte-identical per (seed, config))."""
        with self._lock:
            n = self._n
            bins = []
            for k in range(self._bins):
                c = int(self._bin_count[k])
                bins.append({
                    "lo": round(k / self._bins, 2),
                    "hi": round((k + 1) / self._bins, 2),
                    "count": c,
                    "mean_p": (
                        round(float(self._bin_p_sum[k]) / c, 4) if c else None
                    ),
                    "mean_y": (
                        round(float(self._bin_y_sum[k]) / c, 4) if c else None
                    ),
                })
            ece = ece_from_bins(self._bin_p_sum, self._bin_y_sum, n)
            out = {
                "matches_scored": n,
                "brier": round(self._brier_sum / n, 6) if n else None,
                "logloss": round(self._logloss_sum / n, 6) if n else None,
                "ece": round(ece, 6) if ece is not None else None,
                "min_matches": int(QUALITY_TABLE["min_matches"]),
                "bins": bins,
                "retained": len(self._z),
                "drift": self._drift,
            }
        out["worst_bin"] = self.worst_bin()
        return out


def score_table(table, stream, cfg) -> dict:
    """The replay judge: scores EVERY ratable match of ``stream``
    against ONE frozen host ``table`` — how well would this table have
    predicted this window? Used by ``cli migrate`` (and the soak's
    migration block) to compare the staging lineage's post-backfill
    table against the pre-migration live table over the same replay
    window: the dual-lineage engine as a counterfactual what-if judge.

    Hindsight caveat: the table already saw these matches (the backfill
    rated them), so this measures FIT over the window, not forward
    prediction — apples-to-apples between the two lineages because both
    score the identical stream with the identical link."""
    table = np.asarray(table)
    ledger = CalibrationLedger(cfg, mirror=False)
    pad_row = table.shape[0] - 1
    player_idx = np.asarray(stream.player_idx)
    # Rows beyond the frozen table (a stream wider than the lineage)
    # clip into the pad row, dropping out of the team reduction like
    # any padding slot — the gather stays in bounds either way.
    player_idx = np.where(player_idx >= pad_row, pad_row, player_idx)
    ledger.score_batch(
        table,
        player_idx,
        np.asarray(stream.winner),
        np.asarray(stream.mode_id),
        np.asarray(stream.afk),
        pad_row=pad_row,
    )
    summary = ledger.summary()
    del summary["drift"]
    return summary


_LEDGER: CalibrationLedger | None = None


def set_quality_ledger(ledger: CalibrationLedger | None) -> None:
    """Registers the process's live ledger (the worker's) so the
    ``/qualityz`` route and ``cli quality`` can reach it."""
    global _LEDGER
    _LEDGER = ledger


def get_quality_ledger() -> CalibrationLedger | None:
    return _LEDGER


def reset_quality_ledger() -> None:
    set_quality_ledger(None)


def render_quality(summary: dict) -> str:
    """Human rendering of a quality summary: the reliability table,
    the streaming scores, and the drift verdict (``cli quality``)."""
    lines = []
    n = summary.get("matches_scored", 0)
    lines.append(
        "quality: %s matches scored, brier=%s logloss=%s ece=%s"
        % (n, summary.get("brier"), summary.get("logloss"),
           summary.get("ece"))
    )
    lines.append("  bin        count  mean_p  mean_y")
    for b in summary.get("bins", []):
        lines.append(
            "  [%.1f,%.1f) %6d  %6s  %6s"
            % (b["lo"], b["hi"], b["count"],
               "-" if b["mean_p"] is None else "%.3f" % b["mean_p"],
               "-" if b["mean_y"] is None else "%.3f" % b["mean_y"])
        )
    wb = summary.get("worst_bin")
    if wb is not None:
        lines.append(
            "  worst bin [%s,%s): gap=%s over %s matches"
            % (wb["lo"], wb["hi"], wb["gap"], wb["count"])
        )
    drift = summary.get("drift")
    if drift is not None:
        verdict = "DRIFTING" if drift.get("psi_alert") else "stable"
        lines.append(
            "drift: %s — psi_mu=%s over %s rated rows"
            % (verdict, drift.get("psi_mu"), drift.get("rated_rows"))
        )
        lines.append(
            "  sigma by games-played cohort: %s"
            % (drift.get("sigma_by_cohort"),)
        )
    else:
        lines.append("drift: no snapshot yet")
    if "temperature" in summary:
        t = summary["temperature"]
        lines.append(
            "temperature: T=%s (nll %s -> %s over %s retained)"
            % (t["t"], t["nll_before"], t["nll_after"], t["n"])
        )
    return "\n".join(lines) + "\n"
