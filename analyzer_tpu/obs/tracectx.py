"""Causal trace context: follow one match from enqueue to served-visible.

The PR-2 tracer records *what* each thread was doing; nothing connected
a specific match's broker message to the batch that rated it, the feed
windows that staged it, the commit that made it durable, and the view
version that made it queryable. This module is that connective tissue:

  * :func:`mint` creates a :class:`TraceContext` — ``(trace_id,
    parent span id, enqueue timestamp)`` — when a match enters the
    broker, and :func:`headers` / :func:`from_headers` carry it through
    the message headers (``x-trace-id`` / ``x-parent-span`` /
    ``x-enqueue-us``), so the worker can compute queue wait without any
    shared state with the publisher;
  * :func:`assemble` is the worker-side join point: one
    ``batch.assemble`` instant records which match traces entered which
    batch (the batch gets its own ``b<N>`` trace id), and
    :func:`~analyzer_tpu.obs.tracer.bind_trace` then tags every span the
    batch's pipeline emits — encode, pack, the feed thread's
    materialize/transfer, dispatch, the writer thread's fetch/commit,
    and the view publish — with that id, turning the Perfetto export
    into a linked tree across threads instead of disjoint lanes;
  * ``analyzer_tpu/obs/traceview.py`` reconstructs per-match and
    per-batch timelines from the tagged events (``cli trace``).

Cost contract: **zero-allocation when disabled**. Every entry point
checks one module-level bool first and returns ``None`` untouched —
no ids are minted, no headers attached, no instants emitted, and the
tracer's per-event context lookup finds an empty thread-local. Enabling
tracing must also never perturb behavior: ids come from a process-local
counter and timestamps are only ever *recorded*, never branched on, so
the soak's bit-identical deterministic block survives tracing verbatim
(pinned by tests/test_trace.py).

Enable via :func:`enable_tracing`, ``ANALYZER_TPU_TRACE=1``, or the
owning entry points (``cli soak --trace`` / ``SoakConfig(trace=True)``).
"""

from __future__ import annotations

import itertools
import os
import threading
import time

from analyzer_tpu.obs.tracer import bind_trace, current_trace, get_tracer

__all__ = [
    "TraceContext",
    "assemble",
    "bind_trace",
    "current_trace",
    "enable_tracing",
    "from_headers",
    "headers",
    "mint",
    "tracing_enabled",
]

ENV_TRACE = "ANALYZER_TPU_TRACE"

#: Broker message header keys. String values only — AMQP header tables
#: round-trip strings untouched; numbers would be at the mercy of the
#: client library's type mapping.
TRACE_HEADER = "x-trace-id"
PARENT_HEADER = "x-parent-span"
ENQUEUE_HEADER = "x-enqueue-us"

_enabled = bool(os.environ.get(ENV_TRACE, ""))
_ids = itertools.count(1)
_ids_lock = threading.Lock()


def tracing_enabled() -> bool:
    """Whether causal tracing is on (one module-level bool)."""
    return _enabled


def enable_tracing(on: bool = True) -> None:
    """Flips causal tracing process-wide. Off is the default: every
    propagation entry point becomes a no-op returning ``None``."""
    global _enabled
    _enabled = bool(on)


def next_span_id() -> int:
    """A process-unique id for a span/batch node in the causal tree."""
    with _ids_lock:
        return next(_ids)


class TraceContext:
    """The per-message causal context: which trace (the match id), the
    parent span that minted it, and when it entered the broker — on the
    tracer's microsecond timeline, so queue wait is a same-process
    subtraction against any later event's ``ts``."""

    __slots__ = ("trace_id", "span_id", "enqueue_us")

    def __init__(self, trace_id: str, span_id: int, enqueue_us: float) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.enqueue_us = enqueue_us

    def __repr__(self) -> str:  # pragma: no cover — debugging nicety
        return (
            f"TraceContext({self.trace_id!r}, span={self.span_id}, "
            f"enqueue_us={self.enqueue_us:.1f})"
        )


def mint(trace_id: str) -> TraceContext | None:
    """Mints the context for a match entering the broker and emits the
    ``trace.enqueue`` instant that anchors its timeline. ``None`` when
    tracing is disabled (the zero-cost path: one bool check)."""
    if not _enabled:
        return None
    tracer = get_tracer()
    ctx = TraceContext(str(trace_id), next_span_id(), tracer._now_us())
    tracer.instant("trace.enqueue", cat="trace", trace=ctx.trace_id,
                   span=ctx.span_id)
    return ctx


def headers(ctx: TraceContext | None) -> dict | None:
    """Message headers carrying ``ctx`` (None passes through, so
    ``broker.publish(q, body, headers=headers(mint(id)))`` is safe
    either way)."""
    if ctx is None:
        return None
    return {
        TRACE_HEADER: ctx.trace_id,
        PARENT_HEADER: str(ctx.span_id),
        ENQUEUE_HEADER: f"{ctx.enqueue_us:.1f}",
    }


def from_headers(hdrs: dict | None) -> TraceContext | None:
    """Reconstructs the context a publisher attached; ``None`` when
    tracing is disabled, headers are absent, or the message predates
    tracing (a mixed fleet must keep consuming)."""
    if not _enabled or not hdrs:
        return None
    trace_id = hdrs.get(TRACE_HEADER)
    if not trace_id:
        return None
    try:
        span_id = int(hdrs.get(PARENT_HEADER, 0))
        enqueue_us = float(hdrs.get(ENQUEUE_HEADER, "nan"))
    except (TypeError, ValueError):
        return None
    return TraceContext(str(trace_id), span_id, enqueue_us)


def assemble(messages) -> str | None:
    """The worker-side join: mints the batch's own trace id and records
    the batch membership — one ``batch.assemble`` instant with the
    member match ids and their enqueue timestamps (``None`` for
    messages that carried no context). Bind the returned id with
    :func:`bind_trace` around the batch's pipeline so every span it
    emits joins the tree. ``None`` when tracing is disabled."""
    if not _enabled:
        return None
    batch_trace = f"b{next_span_id()}"
    members: list[str] = []
    enqueues: list[float | None] = []
    for m in messages:
        try:
            members.append(m.body.decode())
        except Exception:  # noqa: BLE001 — a binary body must not kill tracing
            members.append(repr(m.body))
        ctx = from_headers(getattr(m, "headers", None))
        enqueues.append(None if ctx is None else round(ctx.enqueue_us, 1))
    get_tracer().instant(
        "batch.assemble", cat="trace", batch=batch_trace,
        members=members, enqueues=enqueues,
    )
    return batch_trace


def wall_of_us(us: float, tracer=None) -> float:
    """Converts a tracer-timeline microsecond stamp back to wall-clock
    seconds (for human rendering; the analyzer itself never needs
    wall time)."""
    t = tracer or get_tracer()
    return t.epoch_wall + us / 1e6
