"""Device-time attribution: opt-in jax.profiler capture windows.

The host-side trace (obs/tracer.py + obs/tracectx.py) decomposes a
match's journey into queue wait / encode / pack / staging / H2D /
dispatch / commit — but "dispatch" is an enqueue from the host's point
of view, and the ROADMAP's rig questions (fused-vs-scan on v5e, tier
promotion bandwidth, shard spread) need *device* time per dispatch. This
module arms a process-wide :class:`DeviceProfiler` that captures one
``jax.profiler`` trace around the NEXT dispatch window after a request:

  * **operator on demand** — ``SIGUSR2`` on a worker requests a capture
    (force-bypassing the throttle), the next batch's compute runs under
    the profiler, and the capture directory logs;
  * **automatic on failure** — dead-letters and pipeline degradation
    request a throttled capture, so the flight-recorder dump that
    freezes the host-side story gets device timing for the very next
    batch; the dump's ``context.json`` names the capture directory
    (``profile`` block);
  * **always explicit** — nothing captures unless a profile directory
    is configured (``--profile-dir`` / ``ANALYZER_TPU_PROFILE_DIR``);
    unarmed, ``request`` and ``maybe_capture`` are no-ops costing one
    attribute read per batch.

Captures are whole TensorBoard/Perfetto-loadable trace directories —
the same artifact ``utils.profiling.trace`` produces, but scoped to one
dispatch window and triggerable without a code change. The profiler
start/stop never raise into the dispatch path.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time

from analyzer_tpu.logging_utils import get_logger

logger = get_logger(__name__)

ENV_DIR = "ANALYZER_TPU_PROFILE_DIR"

MANIFEST_NAME = "manifest.json"


def _device_identity() -> dict:
    """Best-effort (platform, device_kind) of device 0 — the capture
    must not fail because jax is absent or unhappy."""
    try:
        import jax
    except ImportError:
        return {"platform": None, "device_kind": None}
    try:
        dev = jax.devices()[0]
        return {
            "platform": str(dev.platform),
            "device_kind": str(getattr(dev, "device_kind", "") or ""),
        }
    except Exception:  # noqa: BLE001 — identity is advisory
        return {"platform": None, "device_kind": None}


def _start_trace(path: str) -> None:
    """jax.profiler.start_trace, isolated for tests to stub."""
    import jax

    jax.profiler.start_trace(path)


def _stop_trace() -> None:
    import jax

    jax.profiler.stop_trace()


class DeviceProfiler:
    def __init__(
        self,
        profile_dir: str | None = None,
        min_interval_s: float = 60.0,
        clock=time.monotonic,
    ) -> None:
        self._lock = threading.Lock()
        self.profile_dir = profile_dir or os.environ.get(ENV_DIR) or None
        self.min_interval_s = min_interval_s
        self._clock = clock
        # Reason of the pending capture request; claimed (and cleared)
        # by the next maybe_capture window.
        self._pending: str | None = None
        # Per-reason throttle, like the flight recorder's: a dead-letter
        # storm must not starve an operator's SIGUSR2 (which forces) or
        # a later degradation capture.
        self._last_at: dict[str, float] = {}
        self.captures = 0
        self.last_capture: str | None = None
        self.last_manifest: dict | None = None

    def configure(
        self,
        profile_dir: str | None = None,
        min_interval_s: float | None = None,
    ) -> "DeviceProfiler":
        if profile_dir is not None:
            self.profile_dir = profile_dir
        if min_interval_s is not None:
            self.min_interval_s = min_interval_s
        return self

    @property
    def armed(self) -> bool:
        return self.profile_dir is not None

    def request(self, reason: str, force: bool = False) -> bool:
        """Requests a capture of the next dispatch window. Returns
        whether the request was accepted (False when unarmed or inside
        the reason's throttle window). Safe from signal handlers."""
        if not self.armed:
            return False
        now = self._clock()
        with self._lock:
            last = self._last_at.get(reason)
            if not force and last is not None and (
                now - last < self.min_interval_s
            ):
                return False
            self._last_at[reason] = now
            self._pending = reason
        logger.info("device profiler capture requested (%s)", reason)
        return True

    @contextlib.contextmanager
    def maybe_capture(self, context: dict | None = None):
        """Wraps one dispatch window: a no-op unless a request is
        pending, else the block runs under ``jax.profiler`` into a
        fresh ``profile-<ts>-<reason>-<pid>`` directory with a
        ``manifest.json`` naming the reason, wall window, dispatch
        window ordinal, the trace/batch ids in flight (the thread-bound
        trace id plus whatever the dispatch site passes in ``context``),
        and the device platform — so obs/profview joins capture to
        host trace without filename archaeology. Profiler errors never
        propagate into the dispatch path."""
        if self._pending is None:  # the per-batch fast path: one read
            yield
            return
        with self._lock:
            reason, self._pending = self._pending, None
        if reason is None or self.profile_dir is None:
            yield
            return
        stamp = time.strftime("%Y%m%d-%H%M%S")
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)
        path = os.path.join(
            self.profile_dir, f"profile-{stamp}-{safe}-{os.getpid()}"
        )
        started = False
        manifest: dict | None = None
        try:
            os.makedirs(path, exist_ok=True)
            _start_trace(path)
            started = True
            manifest = self._manifest_start(reason, path, context)
        except Exception:  # noqa: BLE001 — attribution must not kill the batch
            logger.exception("device profiler start failed (%s)", reason)
        try:
            yield
        finally:
            if started:
                try:
                    _stop_trace()
                    self.captures += 1
                    self.last_capture = path
                    if manifest is not None:
                        self._write_manifest(path, manifest)
                    logger.info(
                        "device profiler capture (%s) written to %s",
                        reason, path,
                    )
                except Exception:  # noqa: BLE001 — ditto
                    logger.exception(
                        "device profiler stop failed (%s)", reason
                    )

    def _manifest_start(
        self, reason: str, path: str, context: dict | None
    ) -> dict:
        """The manifest fields knowable at capture start. The bound
        trace id doubles as the batch id at both dispatch sites ("b<N>"
        per worker numbering), so it lands in both lists."""
        from analyzer_tpu.obs.tracer import current_trace

        trace = current_trace()
        manifest = {
            "version": 1,
            "reason": reason,
            "dir": os.path.basename(path),
            # 1-based ordinal of this capture = the dispatch window it
            # wrapped, in profiler order.
            "capture_index": self.captures + 1,
            "wall_start": time.time(),
            "traces": [trace] if trace else [],
            "batches": [trace] if trace else [],
            "device": _device_identity(),
        }
        for key in ("traces", "batches"):
            extra = (context or {}).get(key) or []
            for item in extra:
                if item and item not in manifest[key]:
                    manifest[key].append(str(item))
        for key, value in sorted((context or {}).items()):
            if key not in ("traces", "batches") and key not in manifest:
                manifest[key] = value
        return manifest

    def _write_manifest(self, path: str, manifest: dict) -> None:
        manifest["wall_end"] = time.time()
        try:
            with open(
                os.path.join(path, MANIFEST_NAME), "w", encoding="utf-8"
            ) as f:
                json.dump(manifest, f, sort_keys=True, indent=2)
                f.write("\n")
            self.last_manifest = manifest
        except OSError:
            logger.exception("device profiler manifest write failed")

    def capture_info(self) -> dict | None:
        """The flight-dump context block: None when unarmed, else the
        directory, capture count, the latest capture path (None until
        the first window actually ran), and that capture's manifest
        (reason / wall window / dispatch window / ids in flight)."""
        if not self.armed:
            return None
        return {
            "dir": self.profile_dir,
            "captures": self.captures,
            "last_capture": self.last_capture,
            "last_manifest": self.last_manifest,
        }


_profiler_lock = threading.Lock()
_profiler: DeviceProfiler | None = None


def get_device_profiler() -> DeviceProfiler:
    """The process-wide device profiler (created on first use)."""
    global _profiler
    with _profiler_lock:
        if _profiler is None:
            _profiler = DeviceProfiler()
        return _profiler


def reset_device_profiler(**kwargs) -> DeviceProfiler:
    """Replaces the process-wide profiler with a fresh one (tests)."""
    global _profiler
    with _profiler_lock:
        _profiler = DeviceProfiler(**kwargs)
        return _profiler
