"""Fleet observability plane: metrics federation + fleet-scope SLO burns.

Every observability surface below this module is PROCESS-LOCAL — the
registry, ``/statusz``, the history rings, the SLO watchdog, causal
tracing all answer questions about ONE worker. The moment a second
worker exists (ROADMAP item 2's multi-host fabric), nobody can answer
"which host is burning?" or follow a match that was enqueued on host A
and rated on host B. This module is the fleet half:

  * :class:`Collector` scrapes N workers' obsd endpoints
    (``/debug/snapshot`` for the registry merge, ``/historyz`` for
    per-host sampler staleness), merges their registries into a FLEET
    snapshot under the reserved ``host=`` label (``obs.registry
    .RESERVED_LABELS`` — graftlint GL034 keeps every other call site
    away from it), maintains fleet-level history rings over the merged
    series, and evaluates ``STANDARD_OBJECTIVES`` at fleet scope as
    multi-window burn rates — with PER-HOST attribution, so a fleet
    burn names the offending host, and an evidence hook: at burn onset
    the Collector asks the burning host to freeze its own flight
    recorder via obsd's authenticated-localhost ``/debug/flight``
    trigger (the trajectory INTO the burn is captured on the machine
    that burned, not reconstructed later);
  * :class:`FleetServer` serves the federated view: ``/fleetz``
    (topology + per-host health/versions/staleness), aggregated
    ``/metrics`` (Prometheus text over the merged snapshot), a fleet
    ``/sloz``, and the fleet rings on ``/historyz``;
  * ``cli fleet`` drives both — a scrape loop in serve mode, or
    ``--check`` one-shot mode (scrape once, evaluate, exit 1 on burn)
    so CI gates a multi-process topology like benchdiff gates
    artifacts.

Aggregation semantics: counters SUM across hosts (a dead letter
anywhere moves the fleet delta), gauges take the MAX (the fleet's
``serve.view_age_seconds`` is the WORST host's staleness — exactly the
number the bounded-staleness objective must burn on); histograms merge
as per-host labeled summaries only (quantiles do not add). A host that
drops out of a scrape round simply leaves the merge — its counters'
disappearance DECREASES fleet sums, which the burn-rate windows read as
"no new events", never as a spurious burn.

Clock discipline: like :mod:`obs.history` and :mod:`obs.slo`, this
module NEVER reads a wall clock (graftlint GL034 bans ``time.*`` here)
— ``scrape(now)``/``check(now)`` take the caller's timestamp (``cli
fleet``'s wall loop, a test's synthetic clock), so fleet burn windows
are exactly as deterministic as their driver. Stdlib-only, like the
rest of the exposition layer.
"""

from __future__ import annotations

import dataclasses
import json
import re
import threading

from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs.registry import MAX_LABEL_VALUES, get_registry

logger = get_logger(__name__)

__all__ = [
    "Collector", "FleetServer", "HostState", "MAX_FLEET_HOSTS",
    "fleet_series_key",
]

#: Host-cardinality cap — the ``host=`` label's analog of the
#: registry's per-family label guard (PR 10): targets past the cap are
#: refused at construction (counted in ``fleet.hosts_dropped``), so a
#: mis-generated target list cannot grow the fleet snapshot, the merged
#: rings, and every /fleetz render without bound.
MAX_FLEET_HOSTS = MAX_LABEL_VALUES

#: Fleet history capacity: per-host labeled series multiply the base
#: schema by the host count, so the fleet rings get a wider series cap
#: than a single process's sampler.
MAX_FLEET_SERIES = 16384

_SERIES_RE = re.compile(r"^(?P<name>[^{]+)(\{(?P<labels>.*)\})?$", re.DOTALL)

_TIMEOUT_S = 5.0


def fleet_series_key(key: str, host: str) -> str:
    """``name{a=b}`` + host -> ``name{a=b,host=<target>}`` (labels kept
    sorted, the registry's own key discipline) — the reserved-label
    merge every scraped series goes through."""
    m = _SERIES_RE.match(key)
    name = m.group("name") if m else key
    labels = {}
    body = (m.group("labels") if m else None) or ""
    if body:
        for pair in body.split(","):
            k, _, v = pair.partition("=")
            labels[k] = v
    labels["host"] = host
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def _numeric(value) -> float | None:
    if value is None:
        return None
    if isinstance(value, bool):
        return 1.0 if value else 0.0
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


def _http_fetch_json(url: str, timeout: float = _TIMEOUT_S) -> dict:
    """The default fetcher (tests inject their own): one GET, parsed as
    JSON. Localhost/VPC scrape targets — no retries here; the Collector
    counts failures per host and keeps scraping."""
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.load(resp)


@dataclasses.dataclass
class HostState:
    """One scrape target's rolling state (the /fleetz row)."""

    target: str
    up: bool = False
    scrapes: int = 0
    consecutive_failures: int = 0
    last_scrape_t: float | None = None
    last_error: str | None = None
    snapshot: dict | None = None
    # Lifted from the scrape for the /fleetz row: the served view's
    # version/age gauges and the worker's own history-sampler position
    # (a stalled sampler means the host's burn windows are blind).
    view_version: float | None = None
    view_age_s: float | None = None
    history_last_sample_t: float | None = None
    history_samples: int | None = None

    def row(self) -> dict:
        return {
            "up": self.up,
            "scrapes": self.scrapes,
            "consecutive_failures": self.consecutive_failures,
            "last_scrape_t": self.last_scrape_t,
            "last_error": self.last_error,
            "view_version": self.view_version,
            "view_age_seconds": self.view_age_s,
            "history_last_sample_t": self.history_last_sample_t,
            "history_samples": self.history_samples,
        }


class Collector:
    """The fleet scraper/merger/judge. Clock-injected: drive it with
    :meth:`scrape` at the caller's cadence; read the federated view
    through :meth:`fleet_snapshot` / :meth:`fleetz` / :meth:`sloz`, or
    serve them with :class:`FleetServer`.

    Doubles as the fleet :class:`~analyzer_tpu.obs.history
    .HistorySampler`'s registry: ``snapshot()`` returns the merged
    fleet view, so one unmodified sampler records fleet-level rings the
    unmodified SLO evaluators then burn on — the single-process plane's
    machinery, pointed at the fleet."""

    def __init__(
        self,
        targets,
        objectives=None,
        flight_token: str | None = None,
        request_flight_dumps: bool = True,
        fetch=None,
        max_hosts: int = MAX_FLEET_HOSTS,
        max_series: int = MAX_FLEET_SERIES,
    ) -> None:
        from analyzer_tpu.obs.history import HistorySampler

        targets = [str(t).strip() for t in targets if str(t).strip()]
        reg = get_registry()
        if len(targets) > max_hosts:
            dropped = len(targets) - max_hosts
            logger.warning(
                "fleet host cap: scraping %d of %d targets (%d dropped)",
                max_hosts, len(targets), dropped,
            )
            reg.gauge("fleet.hosts_dropped").set(dropped)
            targets = targets[:max_hosts]
        self.targets = targets
        self._objectives = objectives
        self.flight_token = flight_token
        self.request_flight_dumps = request_flight_dumps
        self._fetch = fetch or _http_fetch_json
        self._lock = threading.Lock()
        self._hosts = {t: HostState(target=t) for t in targets}
        self._merged: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        self._state: dict = {}          # objective name -> Burn
        self._attribution: dict = {}    # objective name -> [targets]
        self.scrapes = 0
        self.last_scrape_t: float | None = None
        self.history = HistorySampler(registry=self, max_series=max_series)
        reg.gauge("fleet.hosts").set(len(targets))

    # -- the registry facade the fleet HistorySampler samples -------------
    def snapshot(self) -> dict:
        return self.fleet_snapshot()

    def counter(self, name: str, **labels):
        # Sampler self-telemetry (history.samples_total) lands on the
        # collector process's own registry, like any other subsystem.
        return get_registry().counter(name, **labels)

    def gauge(self, name: str, **labels):
        return get_registry().gauge(name, **labels)

    # -- scraping ---------------------------------------------------------
    def _scrape_host(self, hs: HostState, now: float) -> None:
        base = f"http://{hs.target}"
        try:
            snap = self._fetch(f"{base}/debug/snapshot")
        except Exception as err:  # noqa: BLE001 — a down host is a state,
            # not a collector crash; the scrape loop must keep going.
            hs.up = False
            hs.consecutive_failures += 1
            hs.last_error = repr(err)
            get_registry().counter("fleet.scrape_errors_total").add(1)
            return
        hs.up = True
        hs.scrapes += 1
        hs.consecutive_failures = 0
        hs.last_error = None
        hs.last_scrape_t = now
        hs.snapshot = snap
        gauges = snap.get("gauges") or {}
        hs.view_version = _numeric(gauges.get("serve.view_version"))
        hs.view_age_s = _numeric(gauges.get("serve.view_age_seconds"))
        try:
            # The worker-side sampler's position, without the series
            # payload (?series= filters to a tiny prefix): a host whose
            # own rings stopped advancing is blind to its local burns —
            # the /fleetz row must say so.
            hist = self._fetch(f"{base}/historyz?series=history.")
            hs.history_last_sample_t = _numeric(hist.get("last_sample_t"))
            hs.history_samples = hist.get("samples")
        except Exception:  # noqa: BLE001 — optional detail, never fatal
            hs.history_last_sample_t = None
            hs.history_samples = None

    def _merge(self) -> dict:
        """The fleet snapshot: per-host series under ``host=`` plus the
        fleet aggregates under the bare names (counters sum, gauges
        max), with the Collector's own ``fleet.*`` self-telemetry
        overlaid."""
        counters: dict = {}
        gauges: dict = {}
        hists: dict = {}
        agg_c: dict = {}
        agg_g: dict = {}
        for hs in self._hosts.values():
            if not hs.up or hs.snapshot is None:
                continue
            for key, value in (hs.snapshot.get("counters") or {}).items():
                v = _numeric(value)
                if v is None:
                    continue
                counters[fleet_series_key(key, hs.target)] = v
                agg_c[key] = agg_c.get(key, 0.0) + v
            for key, value in (hs.snapshot.get("gauges") or {}).items():
                v = _numeric(value)
                if v is None:
                    continue
                gauges[fleet_series_key(key, hs.target)] = v
                prev = agg_g.get(key)
                agg_g[key] = v if prev is None else max(prev, v)
            for key, summ in (hs.snapshot.get("histograms") or {}).items():
                if isinstance(summ, dict):
                    hists[fleet_series_key(key, hs.target)] = dict(summ)
        counters.update(agg_c)
        gauges.update(agg_g)
        own = get_registry().snapshot()
        counters.update({
            k: v for k, v in own["counters"].items()
            if k.startswith("fleet.")
        })
        gauges.update({
            k: v for k, v in own["gauges"].items() if k.startswith("fleet.")
        })
        return {
            "counters": dict(sorted(counters.items())),
            "gauges": dict(sorted(gauges.items())),
            "histograms": dict(sorted(hists.items())),
        }

    def scrape(self, now: float) -> list:
        """One federation round at the caller's timestamp: scrape every
        target, rebuild the fleet snapshot, record a fleet history
        sample, evaluate the objective table at fleet scope, and fire
        evidence capture at burn onsets. Returns the live objectives'
        fleet burn states."""
        reg = get_registry()
        with self._lock:
            for hs in self._hosts.values():
                self._scrape_host(hs, now)
            self.scrapes += 1
            self.last_scrape_t = now
            reg.counter("fleet.scrapes_total").add(1)
            for hs in self._hosts.values():
                reg.gauge("fleet.host_up", host=hs.target).set(hs.up)
            # Merge AFTER the self-telemetry bump so the fleet snapshot
            # (and the rings sampled from it) carries this round's own
            # fleet.* counters.
            self._merged = self._merge()
        # Outside the lock: the sampler re-enters snapshot() (which
        # takes the lock) and the burn hook does network IO.
        self.history.sample(now)
        reg.gauge("fleet.series").set(len(self.history.names()))
        return self._evaluate(now)

    # -- fleet-scope evaluation -------------------------------------------
    def objectives(self):
        from analyzer_tpu.obs.slo import STANDARD_OBJECTIVES

        return (
            STANDARD_OBJECTIVES if self._objectives is None
            else tuple(self._objectives)
        )

    def _host_objective(self, obj, target: str):
        return dataclasses.replace(
            obj,
            metric=fleet_series_key(obj.metric, target),
            metric_b=(
                fleet_series_key(obj.metric_b, target)
                if obj.metric_b else None
            ),
        )

    def _evaluate(self, now: float) -> list:
        from analyzer_tpu.obs.slo import LIVE_KINDS, Burn, evaluate_live

        reg = get_registry()
        results: list = []
        onsets: list = []
        with self._lock:
            up = [t for t, hs in self._hosts.items() if hs.up]
            for obj in self.objectives():
                if obj.kind not in LIVE_KINDS:
                    continue
                try:
                    burn = evaluate_live(obj, self.history, now)
                except Exception as err:  # noqa: BLE001 — one broken
                    # evaluator must not stop the fleet pass.
                    burn = Burn(obj.name, False, None, f"error: {err!r}")
                attributed: list = []
                if burn.burning:
                    # Per-host attribution: re-run the same evaluator
                    # over the host-labeled series. A burn no single
                    # host owns (each under threshold, the sum over) is
                    # attributed to the fleet as a whole.
                    for target in up:
                        try:
                            hb = evaluate_live(
                                self._host_objective(obj, target),
                                self.history, now,
                            )
                        except Exception:  # noqa: BLE001 — as above
                            continue
                        if hb.burning:
                            attributed.append(target)
                prev = self._state.get(obj.name)
                was_burning = prev is not None and prev.burning
                if burn.burning and not was_burning:
                    reg.counter("fleet.burns_total").add(1)
                    onsets.append((obj, burn, list(attributed)))
                elif not burn.burning and was_burning:
                    reg.counter("fleet.recoveries_total").add(1)
                self._state[obj.name] = burn
                self._attribution[obj.name] = attributed
                results.append(burn)
            reg.gauge("fleet.burning").set(
                sum(1 for b in self._state.values() if b.burning)
            )
        for obj, burn, attributed in onsets:
            logger.warning(
                "FLEET SLO burn: %s on %s — %s",
                obj.name, attributed or "the fleet (no single host)",
                burn.detail,
            )
            if self.request_flight_dumps:
                for target in attributed:
                    self._request_flight(target, obj.name)
        return results

    def _request_flight(self, target: str, objective: str) -> None:
        """Evidence capture at burn onset: the burning host freezes its
        own flight recorder via obsd's /debug/flight trigger (localhost
        -authenticated there; the shared token rides the query). Best
        effort — the fleet keeps judging whether or not the evidence
        lands."""
        url = f"http://{target}/debug/flight?reason=fleet-slo-{objective}"
        if self.flight_token:
            url += f"&token={self.flight_token}"
        try:
            got = self._fetch(url)
            get_registry().counter("fleet.flight_requests_total").add(1)
            logger.info(
                "requested flight dump from %s: %s", target,
                (got or {}).get("dumped"),
            )
        except Exception as err:  # noqa: BLE001 — evidence is best-effort
            logger.warning(
                "flight-dump request to %s failed: %r", target, err
            )

    def check(self, now: float) -> list:
        """One-shot mode (``cli fleet --check``): a SINGLE scrape, then
        absolute evaluation of the objectives a lone sample can judge —
        ``counter_zero`` objectives on the counters' absolute values
        (the CI topology under test is freshly started, so any count IS
        this run's count) and ``gauge_max`` on the merged worst-host
        gauges. Rate/growth/ratio objectives need two samples and are
        skipped. Returns ``(burn, attributed_targets)`` pairs for the
        burning objectives; an empty list is a green topology."""
        from analyzer_tpu.obs.slo import Burn

        self.scrape(now)
        out: list = []
        with self._lock:
            merged = self._merged
            up = [t for t, hs in self._hosts.items() if hs.up]
            for obj in self.objectives():
                if obj.kind == "counter_zero":
                    value = merged["counters"].get(obj.metric, 0.0)
                    if value <= obj.threshold:
                        continue
                    attributed = [
                        t for t in up
                        if merged["counters"].get(
                            fleet_series_key(obj.metric, t), 0.0
                        ) > obj.threshold
                    ]
                    out.append((
                        Burn(
                            obj.name, True, value,
                            f"{obj.metric} = {value:g} across the fleet "
                            f"(SLO: <= {obj.threshold:g})",
                        ),
                        attributed,
                    ))
                elif obj.kind == "gauge_max":
                    value = merged["gauges"].get(obj.metric)
                    if value is None or value <= obj.threshold:
                        continue
                    attributed = [
                        t for t in up
                        if (merged["gauges"].get(
                            fleet_series_key(obj.metric, t)
                        ) or 0.0) > obj.threshold
                    ]
                    out.append((
                        Burn(
                            obj.name, True, value,
                            f"{obj.metric} worst-host {value:g} "
                            f"(SLO: <= {obj.threshold:g})",
                        ),
                        attributed,
                    ))
        return out

    # -- the federated read surface ---------------------------------------
    def fleet_snapshot(self) -> dict:
        with self._lock:
            return self._merged

    @property
    def burning(self) -> list:
        with self._lock:
            return sorted(
                n for n, b in self._state.items() if b.burning
            )

    def attribution(self) -> dict:
        with self._lock:
            return {
                n: list(t) for n, t in self._attribution.items() if t
            }

    def fleetz(self) -> dict:
        """The ``/fleetz`` payload: topology + per-host health/versions/
        staleness + the fleet burn state."""
        with self._lock:
            hosts = {t: hs.row() for t, hs in self._hosts.items()}
            return {
                "version": 1,
                "targets": len(self.targets),
                "up": sum(1 for hs in self._hosts.values() if hs.up),
                "scrapes": self.scrapes,
                "last_scrape_t": self.last_scrape_t,
                "hosts": hosts,
                "burning": sorted(
                    n for n, b in self._state.items() if b.burning
                ),
                "attribution": {
                    n: list(t)
                    for n, t in self._attribution.items() if t
                },
            }

    def sloz(self) -> dict:
        """The fleet ``/sloz`` payload: the objective table with
        fleet-scope burn states and per-host attribution."""
        from analyzer_tpu.obs.slo import LIVE_KINDS

        with self._lock:
            state = dict(self._state)
            attribution = {
                n: list(t) for n, t in self._attribution.items()
            }
        objs = []
        for obj in self.objectives():
            burn = state.get(obj.name)
            objs.append({
                "name": obj.name,
                "kind": obj.kind,
                "metric": obj.metric or None,
                "threshold": obj.threshold,
                "windows": list(obj.windows),
                "state": (
                    "untracked" if obj.kind not in LIVE_KINDS
                    else "burning" if burn is not None and burn.burning
                    else "ok" if burn is not None
                    else "unevaluated"
                ),
                "value": burn.value if burn is not None else None,
                "detail": (
                    burn.detail if burn is not None else obj.description
                ),
                "hosts": attribution.get(obj.name) or [],
            })
        return {
            "scope": "fleet",
            "objectives": objs,
            "burning": sorted(
                n for n, b in state.items() if b.burning
            ),
            "scrapes": self.scrapes,
        }


class FleetServer:
    """The Collector's serving plane — the fleet analog of obsd, on the
    shared ``obs/httpd.py`` plumbing (loopback by default, GL024)."""

    def __init__(self, collector: Collector, port: int = 0,
                 host: str | None = None) -> None:
        from analyzer_tpu.obs.httpd import (
            DEFAULT_HOST, RoutedHTTPServer, json_body, text_body,
        )
        from analyzer_tpu.obs.snapshot import prometheus_text

        self.collector = collector

        def fleetz(params):
            return json_body(collector.fleetz())

        def sloz(params):
            return json_body(collector.sloz())

        def metrics(params):
            return text_body(prometheus_text(collector.fleet_snapshot()))

        def historyz(params):
            from analyzer_tpu.obs.history import TIERS

            tier = params.get("tier")
            if tier is not None and tier not in {t for t, _, _ in TIERS}:
                return text_body(
                    f"unknown tier {tier!r} (raw|10s|1m)\n", 400
                )
            return json_body(
                collector.history.to_json(
                    prefix=params.get("series"), tier=tier,
                )
            )

        self._httpd = RoutedHTTPServer(
            routes={
                "/healthz": lambda params: text_body("ok\n"),
                "/fleetz": fleetz,
                "/sloz": sloz,
                "/metrics": metrics,
                "/historyz": historyz,
            },
            port=port,
            host=host or DEFAULT_HOST,
            name="analyzer-fleetd",
        )
        logger.info("fleetd listening on %s", self.url)

    @property
    def port(self) -> int:
        return self._httpd.port

    @property
    def url(self) -> str:
        return self._httpd.url

    def close(self) -> None:
        self._httpd.close()
