"""Device-memory telemetry: HBM occupancy and live-buffer gauges.

The 10M-match streaming re-rate (BASELINE.json) carries a multi-GB
working set on device — the player table, the in-flight schedule slabs,
the pipeline's chain ring. Nothing surfaced how close a run sits to the
HBM ceiling until it OOMs. This module samples per-device memory into
the registry at batch boundaries (``sched/runner.py``) and on demand
(bench ``telemetry`` block, ``/metrics``):

  ``device.hbm_bytes_in_use{device=...}``  allocator bytes in use
                                           (``device.memory_stats()``);
  ``device.hbm_bytes_limit{device=...}``   allocator limit when reported;
  ``device.live_buffers{device=...}``      live jax arrays on the device;
  ``device.live_buffers``                  process total.

CPU fallback (tier-1 runs on the CPU backend, where ``memory_stats()``
returns None): bytes-in-use is reconstructed from ``jax.live_arrays()``
nbytes, attributed per device (a sharded array splits evenly across its
device set). The sampler throttles itself (``maybe_sample``) because
``live_arrays`` walks every live buffer — fine per batch, wasteful per
chunk on a deep schedule.

These gauges also ride the telemetry history rings: the worker
registers :func:`maybe_sample` as a pre-sample probe on the history
sampler (``obs/history.py``), so ``device.hbm_bytes_in_use``,
``device.live_buffers`` and ``tier.host_bytes`` are refreshed ahead of
every history row — HBM growth and cold-tier growth become trends an
operator can see (and the ``bounded-memory-growth`` burn-rate SLO in
``obs/slo.py`` can alarm on), not two numbers to subtract by hand.
"""

from __future__ import annotations

import threading
import time

from analyzer_tpu.obs.registry import get_registry

#: Minimum seconds between throttled samples (maybe_sample).
MIN_SAMPLE_INTERVAL_S = 1.0

_lock = threading.Lock()
_last_sample_at: float | None = None

#: Host cold-tier byte probe (``sched/tier.py`` registers one when the
#: first TierManager is built). Sampling it HERE, next to the HBM
#: gauges, is deliberate: the tiered table's budget question is always
#: "device bytes vs host bytes", and one /statusz scrape must answer
#: both sides (``tier.host_bytes`` in the same snapshot as
#: ``device.hbm_bytes_in_use``).
_host_tier_sampler = None


def set_host_tier_sampler(fn) -> None:
    """Registers the callable that reports the cold tier's committed
    host bytes (pinned/committed numpy buffers of every live tier
    manager). One process-wide probe; None clears it (tests)."""
    global _host_tier_sampler
    _host_tier_sampler = fn


def sample_device_memory(registry=None) -> dict:
    """Samples every jax device's memory state into gauges; returns
    ``{device_label: {"bytes_in_use", "bytes_limit", "live_buffers",
    "source"}}``. Imports jax lazily — the obs package stays importable
    without an accelerator stack."""
    import jax

    reg = registry or get_registry()
    per_dev_count: dict = {}
    per_dev_bytes: dict = {}
    live = jax.live_arrays()
    for arr in live:
        try:
            devs = arr.devices()
            nbytes = arr.nbytes
        except Exception:  # noqa: BLE001 — deleted/donated buffers race the walk
            continue
        share = nbytes / max(1, len(devs))
        for d in devs:
            per_dev_count[d] = per_dev_count.get(d, 0) + 1
            per_dev_bytes[d] = per_dev_bytes.get(d, 0.0) + share
    out: dict = {}
    for dev in jax.devices():
        label = f"{dev.platform}:{dev.id}"
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — backends without allocator stats
            stats = None
        if stats and stats.get("bytes_in_use") is not None:
            in_use = int(stats["bytes_in_use"])
            limit = stats.get("bytes_limit")
            source = "memory_stats"
        else:
            in_use = int(per_dev_bytes.get(dev, 0))
            limit = None
            source = "live_arrays"
        count = per_dev_count.get(dev, 0)
        reg.gauge("device.hbm_bytes_in_use", device=label).set(in_use)
        if limit is not None:
            reg.gauge("device.hbm_bytes_limit", device=label).set(int(limit))
        reg.gauge("device.live_buffers", device=label).set(count)
        out[label] = {
            "bytes_in_use": in_use,
            "bytes_limit": int(limit) if limit is not None else None,
            "live_buffers": count,
            "source": source,
        }
    reg.gauge("device.live_buffers").set(len(live))
    if _host_tier_sampler is not None:
        try:
            tier_bytes = int(_host_tier_sampler())
        except Exception:  # noqa: BLE001 — telemetry stays off the failure path
            tier_bytes = None
        if tier_bytes is not None:
            reg.gauge("tier.host_bytes").set(tier_bytes)
            out["host"] = {"tier_bytes": tier_bytes}
    return out


def maybe_sample(min_interval_s: float = MIN_SAMPLE_INTERVAL_S) -> bool:
    """Throttled :func:`sample_device_memory` for batch-boundary call
    sites: the first call always samples, later calls only after
    ``min_interval_s``. Returns whether a sample ran. Never raises — a
    gauge must not take down a rating loop."""
    global _last_sample_at
    now = time.monotonic()
    with _lock:
        if (
            _last_sample_at is not None
            and now - _last_sample_at < min_interval_s
        ):
            return False
        _last_sample_at = now
    try:
        sample_device_memory()
    except Exception:  # noqa: BLE001 — telemetry stays off the failure path
        return False
    return True


def reset_sampler() -> None:
    """Clears the throttle window (tests)."""
    global _last_sample_at
    with _lock:
        _last_sample_at = None
