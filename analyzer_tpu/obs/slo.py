"""Declarative SLO engine: one objective table, three consumers.

Before this module the package enforced its service-level objectives in
three UNRELATED places: ``SoakDriver`` computed a verdict,
``cli benchdiff --family soak`` re-derived the same checks from the
artifact, and a live ``cli worker`` enforced nothing at all — a
violated objective in production was a dashboard squint, not an alarm.
This module promotes the soak's SLO table into ONE declarative
objective set (:data:`STANDARD_OBJECTIVES`) with two evaluation modes:

  * **artifact mode** (:func:`soak_violations`) — re-derives a verdict
    from a SOAK artifact's deterministic block. ``SoakDriver`` and
    ``obs.benchdiff.soak_slo_violations`` (the CI gate) both call THIS
    function, so the driver's verdict and the gate's literally cannot
    drift — and because the live watchdog walks the same objective
    table, doctoring one objective trips all three consumers (pinned
    by test);
  * **live mode** (:func:`evaluate_live`, :class:`Watchdog`) — multi-
    window burn rates over the history rings (:mod:`obs.history`). An
    objective *burns* when every configured window exceeds its
    threshold (the classic short-AND-long window alerting shape: the
    short window gives fast detection, the long window keeps a single
    blip from paging). The :class:`Watchdog` rides the worker's poll
    loop: on a first burn it flips ``/readyz`` degraded (via its
    HealthChecks probe), fires the flight recorder + DeviceProfiler
    through its ``on_burn`` hook, and emits ``slo.*`` state metrics;
    recovery is recorded symmetrically.

Clock discipline: like :mod:`obs.history`, this module NEVER reads a
wall clock (graftlint GL032) — ``Watchdog.check(now)`` and every
evaluator take the caller's timestamp, so under the soak the whole
engine runs on the virtual clock and the deterministic block is
bit-identical with the watchdog on or off.

Objective ``metric`` names must resolve to the pre-declared STANDARD
schema (``obs.registry``) — graftlint GL032 fails a typo'd name at lint
time, because at runtime it would simply never burn.
"""

from __future__ import annotations

import dataclasses
import threading

from analyzer_tpu.obs.registry import get_registry
from analyzer_tpu.obs.quality import QUALITY_TABLE as _QUALITY_TABLE

#: Live evaluation kinds (docs/observability.md "SLO engine"):
#:   counter_zero  any increment over the short window burns
#:                 (zero-tolerance: dead letters, audit mismatches)
#:   counter_rate  events/s above threshold over EVERY window burns
#:   gauge_max     window max above threshold over EVERY window burns
#:   gauge_growth  (last-first)/span above threshold over EVERY window
#:                 burns (the memory-leak burn rate)
#:   ratio_min     metric/(metric+metric_b) delta-ratio over the longest
#:                 window below threshold burns (tier hit-rate floor);
#:                 skipped below ``min_volume`` events
#:   calibration   windowed expected calibration error, computed EXACTLY
#:                 from the labeled ``quality.bin_p_sum{bin=}`` /
#:                 ``bin_y_sum{bin=}`` ring deltas normalized by the
#:                 ``metric`` (scored-matches) delta, above threshold
#:                 over the longest window burns; skipped below
#:                 ``min_volume`` scored matches (obs/quality.py)
#:   artifact      no live half — artifact-mode check only
LIVE_KINDS = (
    "counter_zero", "counter_rate", "gauge_max", "gauge_growth", "ratio_min",
    "calibration",
)


@dataclasses.dataclass(frozen=True)
class Objective:
    """One named service-level objective. ``metric``/``metric_b`` name
    pre-declared STANDARD series (graftlint GL032 enforces resolution);
    ``artifact_check`` names the deterministic-block check
    :func:`soak_violations` runs for it (None = live-only)."""

    name: str
    kind: str
    metric: str = ""
    threshold: float = 0.0
    windows: tuple = (60.0, 300.0)
    metric_b: str | None = None
    min_volume: float = 0.0
    artifact_check: str | None = None
    description: str = ""


@dataclasses.dataclass(frozen=True)
class Burn:
    """One live evaluation result."""

    objective: str
    burning: bool
    value: float | None
    detail: str


#: THE objective table — the soak SLO table promoted to one shared,
#: declarative set. Artifact checks reproduce the historical
#: ``soak_slo_violations`` semantics verbatim; live halves watch the
#: same conditions as burn rates over the history rings.
STANDARD_OBJECTIVES = (
    Objective(
        "zero-dead-letters", "counter_zero", "worker.dead_letters_total",
        artifact_check="dead_letters",
        description="a dead-lettered match is lost work (SLO: 0)",
    ),
    Objective(
        "flat-steady-retraces", "counter_rate", "jax.retraces_total",
        threshold=0.1, artifact_check="retraces_steady",
        description=(
            "post-warmup XLA retraces mean an unwarmed shape reached "
            "production (live: a sustained storm, not one stray compile)"
        ),
    ),
    Objective(
        "bounded-view-staleness", "gauge_max", "serve.view_age_seconds",
        threshold=30.0, windows=(60.0,), artifact_check="view_staleness",
        description=(
            "served ratings must track commits (artifact: lag ticks; "
            "live: seconds since the last publish)"
        ),
    ),
    Objective(
        "drained-backlog", "artifact", artifact_check="drained",
        description="the soak's backlog must clear in bounded time",
    ),
    Objective(
        "no-lost-work", "artifact", artifact_check="lost_work",
        description="every published match must be rated",
    ),
    Objective(
        "throughput-floor", "artifact", artifact_check="throughput_floor",
        description="optional absolute matches/s floor (slo.thresholds)",
    ),
    Objective(
        "latency-cap", "artifact", artifact_check="latency_cap",
        description="optional absolute serve-p99 cap (slo.thresholds)",
    ),
    Objective(
        "no-forbidden-dominant-stage", "artifact",
        artifact_check="dominant_stage",
        description=(
            "the critical path must not be dominated by a forbidden "
            "stage (requires a traced capture)"
        ),
    ),
    Objective(
        "no-feed-starvation", "counter_rate", "feed.starved_total",
        threshold=1.0,
        description=(
            "a starved device feed means the host is the bottleneck "
            "(docs/observability.md feed section)"
        ),
    ),
    Objective(
        "tier-hit-rate-floor", "ratio_min", "tier.hits_total",
        metric_b="tier.misses_total", threshold=0.5, min_volume=1024.0,
        description=(
            "hot-set hit rate collapse = tier thrash (docs/kernels.md); "
            "evaluated only past min_volume touched rows"
        ),
    ),
    Objective(
        "zero-audit-mismatches", "counter_zero", "audit.mismatches_total",
        artifact_check="audit_mismatches",
        description=(
            "the shadow audit replays served answers through the "
            "bit-exact oracle — one mismatch is a correctness incident "
            "(obs/audit.py)"
        ),
    ),
    Objective(
        "calibration-floor", "calibration", "quality.matches_scored_total",
        threshold=_QUALITY_TABLE["ece_alert"],
        min_volume=float(_QUALITY_TABLE["min_matches"]),
        artifact_check="calibration",
        description=(
            "windowed expected calibration error of served win "
            "probabilities vs realized outcomes — the first MODEL-"
            "QUALITY objective (obs/quality.py); evaluated only past "
            "min_volume scored matches, thresholds shared with the "
            "quality plane's one declared table"
        ),
    ),
    Objective(
        "bounded-memory-growth", "gauge_growth", "device.live_buffers",
        threshold=200.0,
        description=(
            "sustained live-buffer growth across every window is the "
            "leak signature (devicemem rides the history sampler)"
        ),
    ),
)


def _objectives(objectives):
    """None -> the CURRENT module-level table (resolved at call time so
    a test can doctor ``STANDARD_OBJECTIVES`` and see every consumer —
    driver, gate, watchdog — pick the doctored set up)."""
    return STANDARD_OBJECTIVES if objectives is None else tuple(objectives)


# -- live mode -------------------------------------------------------------

def evaluate_live(obj: Objective, history, now: float) -> Burn:
    """One objective's burn state over the history rings at ``now``.
    Insufficient history (young process, metric never sampled) is NOT
    burning — an alarm that fires before there is evidence teaches
    operators to ignore it."""
    if obj.kind == "counter_zero":
        got = history.window_delta(obj.metric, obj.windows[0], now)
        if got is None:
            return Burn(obj.name, False, None, "no history yet")
        delta, span = got
        burning = delta > obj.threshold
        return Burn(
            obj.name, burning, delta,
            f"{obj.metric} +{delta:g} over {span:g}s "
            f"(SLO: <= {obj.threshold:g})",
        )
    if obj.kind == "counter_rate":
        rates = []
        for w in obj.windows:
            got = history.window_delta(obj.metric, w, now)
            if got is None:
                return Burn(obj.name, False, None, "no history yet")
            delta, span = got
            rates.append(delta / span if span > 0 else 0.0)
        burning = all(r > obj.threshold for r in rates)
        return Burn(
            obj.name, burning, max(rates),
            f"{obj.metric} rates "
            + "/".join(f"{r:.3g}/s" for r in rates)
            + f" over {'/'.join(f'{w:g}s' for w in obj.windows)} "
            f"(SLO: <= {obj.threshold:g}/s in some window)",
        )
    if obj.kind == "gauge_max":
        maxima = []
        for w in obj.windows:
            m = history.window_max(obj.metric, w, now)
            if m is None:
                return Burn(obj.name, False, None, "no history yet")
            maxima.append(m)
        burning = all(m > obj.threshold for m in maxima)
        return Burn(
            obj.name, burning, max(maxima),
            f"{obj.metric} max {max(maxima):g} "
            f"(SLO: <= {obj.threshold:g})",
        )
    if obj.kind == "gauge_growth":
        rates = []
        for w in obj.windows:
            got = history.window_growth(obj.metric, w, now)
            if got is None:
                return Burn(obj.name, False, None, "no history yet")
            delta, span = got
            rates.append(delta / span if span > 0 else 0.0)
        burning = all(r > obj.threshold for r in rates)
        return Burn(
            obj.name, burning, max(rates),
            f"{obj.metric} growing "
            + "/".join(f"{r:+.3g}/s" for r in rates)
            + f" (SLO: <= {obj.threshold:g}/s sustained)",
        )
    if obj.kind == "ratio_min":
        w = obj.windows[-1]
        a = history.window_delta(obj.metric, w, now)
        b = history.window_delta(obj.metric_b, w, now)
        if a is None or b is None:
            return Burn(obj.name, False, None, "no history yet")
        hits, misses = a[0], b[0]
        volume = hits + misses
        if volume < obj.min_volume:
            return Burn(
                obj.name, False, None,
                f"below min volume ({volume:g} < {obj.min_volume:g})",
            )
        ratio = hits / volume
        return Burn(
            obj.name, ratio < obj.threshold, ratio,
            f"{obj.metric}/({obj.metric}+{obj.metric_b}) = {ratio:.3f} "
            f"over {w:g}s (SLO: >= {obj.threshold:g})",
        )
    if obj.kind == "calibration":
        # Exact windowed ECE from ring deltas: counters sum, so
        # sum_b |Δbin_p_sum_b - Δbin_y_sum_b| / Δscored IS the ECE of
        # exactly the matches scored inside the window (obs/quality.py
        # ece_from_bins documents the identity). The labeled series
        # appear on first score; a bin with no history contributes no
        # gap, which under-counts only if the ring never sampled it —
        # and the volume guard (from the same deltas) covers that.
        w = obj.windows[-1]
        got = history.window_delta(obj.metric, w, now)
        if got is None:
            return Burn(obj.name, False, None, "no history yet")
        total, span = got
        if total < obj.min_volume:
            return Burn(
                obj.name, False, None,
                f"below min volume ({total:g} < {obj.min_volume:g})",
            )
        gap = 0.0
        for k in range(int(_QUALITY_TABLE["bins"])):
            p = history.window_delta(f"quality.bin_p_sum{{bin={k}}}", w, now)
            y = history.window_delta(f"quality.bin_y_sum{{bin={k}}}", w, now)
            if p is not None and y is not None:
                gap += abs(p[0] - y[0])
        ece = gap / total
        return Burn(
            obj.name, ece > obj.threshold, ece,
            f"windowed ece {ece:.3f} over {total:g} matches / {w:g}s "
            f"(SLO: <= {obj.threshold:g})",
        )
    return Burn(obj.name, False, None, f"artifact-only ({obj.kind})")


class Watchdog:
    """The live consumer: evaluates the objective table over the
    history rings on every :meth:`check` and tracks per-objective
    burn/recover state. State transitions emit ``slo.*`` metrics and
    call ``on_burn(objective, burn)`` once per burn onset — the worker
    wires that to a flight-recorder dump + a DeviceProfiler capture
    request, so the evidence window is captured WHILE the objective is
    burning, not reconstructed afterwards."""

    def __init__(self, history=None, objectives=None, on_burn=None) -> None:
        self._history = history
        self._objectives = objectives
        self.on_burn = on_burn
        self._lock = threading.Lock()
        self._state: dict[str, Burn] = {}
        self.checks = 0

    @property
    def history(self):
        if self._history is not None:
            return self._history
        from analyzer_tpu.obs.history import get_history

        return get_history()

    def objectives(self):
        return _objectives(self._objectives)

    def check(self, now: float) -> list[Burn]:
        """One evaluation pass at ``now``; returns every live
        objective's burn state. Never raises."""
        reg = get_registry()
        results: list[Burn] = []
        onsets: list = []
        with self._lock:
            self.checks += 1
            for obj in self.objectives():
                if obj.kind not in LIVE_KINDS:
                    continue
                try:
                    burn = evaluate_live(obj, self.history, now)
                except Exception as err:  # noqa: BLE001 — an evaluator
                    # crash must not take down the poll loop it rides.
                    burn = Burn(obj.name, False, None, f"error: {err!r}")
                prev = self._state.get(obj.name)
                was_burning = prev is not None and prev.burning
                if burn.burning and not was_burning:
                    reg.counter("slo.burns_total").add(1)
                    reg.gauge("slo.state", objective=obj.name).set(1)
                    onsets.append((obj, burn))
                elif not burn.burning and was_burning:
                    reg.counter("slo.recoveries_total").add(1)
                    reg.gauge("slo.state", objective=obj.name).set(0)
                self._state[obj.name] = burn
                results.append(burn)
            reg.gauge("slo.burning").set(
                sum(1 for b in self._state.values() if b.burning)
            )
        for obj, burn in onsets:
            if self.on_burn is not None:
                try:
                    self.on_burn(obj, burn)
                except Exception:  # noqa: BLE001 — evidence capture is
                    # best-effort; the watchdog keeps watching.
                    pass
        return results

    @property
    def burning(self) -> list[str]:
        with self._lock:
            return sorted(
                n for n, b in self._state.items() if b.burning
            )

    def healthy(self):
        """HealthChecks probe: /readyz degrades while any objective
        burns — a balancer should stop preferring a worker that is
        violating its SLOs, which is exactly what a 503 means."""
        burning = self.burning
        if burning:
            return False, "burning: " + ", ".join(burning)
        if not self._state:
            return True, "no SLO evaluation yet"
        return True, f"{len(self._state)} objectives ok"

    def status(self) -> dict:
        """The ``/sloz`` payload."""
        with self._lock:
            state = dict(self._state)
        objs = []
        for obj in self.objectives():
            burn = state.get(obj.name)
            objs.append({
                "name": obj.name,
                "kind": obj.kind,
                "metric": obj.metric or None,
                "threshold": obj.threshold,
                "windows": list(obj.windows),
                "state": (
                    "untracked" if obj.kind not in LIVE_KINDS
                    else "burning" if burn is not None and burn.burning
                    else "ok" if burn is not None
                    else "unevaluated"
                ),
                "value": burn.value if burn is not None else None,
                "detail": (
                    burn.detail if burn is not None else obj.description
                ),
            })
        return {
            "objectives": objs,
            "burning": sorted(
                n for n, b in state.items() if b.burning
            ),
            "checks": self.checks,
        }


_watchdog_lock = threading.Lock()
_watchdog: Watchdog | None = None


def get_watchdog() -> Watchdog:
    """The process-wide watchdog (created on first use; the worker
    attaches its ``on_burn`` hook, /sloz reads its status)."""
    global _watchdog
    with _watchdog_lock:
        if _watchdog is None:
            _watchdog = Watchdog()
        return _watchdog


def reset_watchdog(**kwargs) -> Watchdog:
    """Replaces the process-wide watchdog with a fresh one (tests)."""
    global _watchdog
    with _watchdog_lock:
        _watchdog = Watchdog(**kwargs)
        return _watchdog


# -- artifact mode ---------------------------------------------------------

def _check_dead_letters(data, det, thr, obj):
    dead = det.get("dead_letters", 0)
    if dead:
        return f"dead_letters: {dead} (SLO: 0)"
    return None


def _check_retraces(data, det, thr, obj):
    retraces = det.get("retraces_steady", 0)
    if retraces:
        return (
            f"retraces_steady: {retraces:g} post-warmup retraces "
            "(SLO: flat)"
        )
    return None


def _check_view_staleness(data, det, thr, obj):
    max_lag = thr.get("max_view_lag_ticks", 2)
    lag = det.get("view_lag_ticks_max", 0)
    if lag > max_lag:
        return (
            f"view_lag_ticks_max: {lag} > {max_lag} (served view went "
            "stale while commits were pending)"
        )
    return None


def _check_drained(data, det, thr, obj):
    if not det.get("drained", True) or det.get("queue_depth_final", 0):
        return (
            f"backlog not drained: {det.get('queue_depth_final', '?')} "
            "message(s) left after the drain window"
        )
    return None


def _check_lost_work(data, det, thr, obj):
    published = det.get("matches_published", 0)
    rated = det.get("matches_rated", 0)
    if rated < published:
        return (
            f"matches_rated {rated} < matches_published {published} "
            "(ingest lost work)"
        )
    return None


def _check_throughput_floor(data, det, thr, obj):
    floor = thr.get("min_matches_per_sec")
    if floor is not None and float(data.get("value", 0.0)) < floor:
        return (
            f"matches_per_sec {data.get('value')} below the configured "
            f"floor {floor}"
        )
    return None


def _check_latency_cap(data, det, thr, obj):
    p99_cap = thr.get("max_p99_ms")
    p99 = (data.get("latency_ms") or {}).get("p99")
    if p99_cap is not None and p99 is not None and p99 > p99_cap:
        return f"serve p99 {p99} ms above the configured cap {p99_cap} ms"
    return None


def _check_dominant_stage(data, det, thr, obj):
    forbidden = thr.get("forbid_dominant_stages") or []
    if not forbidden:
        return None
    # Only evaluable on a traced capture; an artifact that ASKED for the
    # gate but carries no trace block fails loudly, not green-by-omission.
    dominant = (data.get("trace") or {}).get("dominant_stage")
    if dominant is None:
        return (
            "forbid_dominant_stages configured but the artifact has "
            "no trace block (run the soak with --trace)"
        )
    if dominant in forbidden:
        return (
            f"dominant critical-path stage {dominant!r} is in the "
            f"forbidden set {sorted(forbidden)} — the ingest edge is "
            "the bottleneck (docs/ingest.md runbook)"
        )
    return None


def _check_audit_mismatches(data, det, thr, obj):
    # The shadow audit's zero-tolerance half: the artifact's audit block
    # rides OUTSIDE the deterministic block (its counters include drains
    # after the measured window), but its mismatch count gates the same
    # as a dead letter. Absent block = audit not enabled = nothing to
    # gate (the soak acceptance run enables it explicitly).
    audit = data.get("audit")
    if not isinstance(audit, dict):
        return None
    mismatches = audit.get("mismatches", 0)
    if mismatches:
        return (
            f"audit mismatches: {mismatches} served response(s) diverged "
            "from the bit-exact oracle (SLO: 0; obs/audit.py)"
        )
    return None


def _check_calibration(data, det, thr, obj):
    # The rating-quality gate (obs/quality.py): the quality block rides
    # OUTSIDE the deterministic block, like audit — the plane is an
    # observer and the deterministic block stays bit-identical with the
    # plane on or off. Absent block = plane off = nothing to gate (the
    # vanished-block regression is benchdiff's job, mirroring the
    # ingest/migrate vanished-native gates); below the volume floor the
    # verdict is withheld, like the live min_volume guard.
    quality = data.get("quality")
    if not isinstance(quality, dict):
        return None
    n = quality.get("matches_scored") or 0
    if n < thr.get("min_quality_matches", obj.min_volume):
        return None
    ece = quality.get("ece")
    cap = thr.get("max_ece", obj.threshold)
    if ece is not None and ece > cap:
        return (
            f"quality ece {ece:g} above {cap:g} over {n} scored matches "
            "(served win probabilities are mis-calibrated; "
            "docs/OPERATIONS.md \"Triaging a calibration burn\")"
        )
    return None


_ARTIFACT_CHECKS = {
    "dead_letters": _check_dead_letters,
    "retraces_steady": _check_retraces,
    "view_staleness": _check_view_staleness,
    "drained": _check_drained,
    "lost_work": _check_lost_work,
    "throughput_floor": _check_throughput_floor,
    "latency_cap": _check_latency_cap,
    "dominant_stage": _check_dominant_stage,
    "audit_mismatches": _check_audit_mismatches,
    "calibration": _check_calibration,
}


def soak_violations(data: dict, objectives=None) -> list[str]:
    """Artifact-mode evaluation: walks the objective table and runs
    each objective's deterministic-block check against a SOAK artifact.
    Returns human-readable violation strings; empty means pass.

    THE shared owner of the soak verdict: ``SoakDriver`` computes its
    artifact's ``slo`` block through this, ``obs.benchdiff``'s
    ``soak_slo_violations`` (the ``cli benchdiff --family soak`` gate)
    delegates here, and the live :class:`Watchdog` walks the same
    table — doctor one objective and all three consumers trip."""
    det = data.get("deterministic")
    if not isinstance(det, dict):
        return ["artifact has no deterministic block (not a SOAK capture?)"]
    thr = (data.get("slo") or {}).get("thresholds") or {}
    out: list[str] = []
    for obj in _objectives(objectives):
        if obj.artifact_check is None:
            continue
        if obj.artifact_check.startswith("zero:"):
            # Generic zero-tolerance check on any deterministic-block
            # key — lets an ad-hoc objective gate a counter without a
            # bespoke check function (and lets tests doctor the table).
            key = obj.artifact_check[5:]
            value = det.get(key, 0)
            if value:
                out.append(
                    f"{key}: {value:g} (SLO: 0; objective {obj.name})"
                )
            continue
        check = _ARTIFACT_CHECKS.get(obj.artifact_check)
        if check is None:
            out.append(
                f"objective {obj.name!r} names unknown artifact check "
                f"{obj.artifact_check!r}"
            )
            continue
        violation = check(data, det, thr, obj)
        if violation is not None:
            out.append(violation)
    return out
