"""Process-wide metrics registry: counters, gauges, histograms.

Design constraints, in order:

  * **stdlib only** — the registry is imported by the scheduler and the
    lint-adjacent CLI paths, which must work without jax/numpy;
  * **cheap on the hot path** — a counter add is one lock acquire and one
    float add; a histogram observation appends to a bounded deterministic
    reservoir (no RNG, no allocation churn);
  * **one process-wide instance** — instruments are identified by
    ``name{label=value,...}`` exactly like Prometheus series, so two call
    sites asking for the same (name, labels) share one instrument, and a
    scraper or a ``--metrics-out`` snapshot sees the whole process.

The registry pre-declares the operator-facing schema (worker gauges,
dead-letter counters, retrace counters — :data:`STANDARD_COUNTERS` /
:data:`STANDARD_GAUGES`) so every snapshot carries the full key set even
before the first event: a dashboard reading ``worker.dead_letters_total``
gets 0, not a missing series that is indistinguishable from a broken
scrape.
"""

from __future__ import annotations

import threading
import time


def _series_key(name: str, labels: dict | None) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic counter. ``rate()`` is anchored at the FIRST sample, not
    construction — a long-lived process whose counter starts moving late
    reports the rate over its active window (the Counters.rate bug this
    replaces measured decaying rates on long-lived workers)."""

    __slots__ = ("_lock", "_value", "_first_at", "_last_at")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0
        self._first_at: float | None = None
        self._last_at: float | None = None

    def add(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        now = time.perf_counter()
        with self._lock:
            if self._first_at is None:
                self._first_at = now
            self._last_at = now
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def rate(self) -> float:
        """Events per second over the first-sample -> now window."""
        with self._lock:
            if self._first_at is None:
                return 0.0
            dt = time.perf_counter() - self._first_at
            return self._value / dt if dt > 0 else 0.0


class Gauge:
    """Last-write-wins scalar. Values may be bool/int/float/None; the
    snapshot passes them through, the Prometheus exposition coerces
    (True -> 1, None -> skipped)."""

    __slots__ = ("_lock", "_value")

    def __init__(self, initial=0) -> None:
        self._lock = threading.Lock()
        self._value = initial

    def set(self, value) -> None:
        with self._lock:
            self._value = value

    def add(self, n: float = 1.0) -> None:
        with self._lock:
            self._value = (self._value or 0) + n

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Streaming distribution with count/sum/min/max and quantiles from a
    DETERMINISTIC decimating reservoir: every ``stride``-th observation is
    kept; when the reservoir hits ``max_samples`` it is halved (even
    indices survive) and the stride doubles. The kept set is an evenly
    spaced subsample of the stream — quantiles are exact for short runs
    and an unbiased-in-time sketch for long ones — with no RNG (results
    are reproducible) and bounded memory."""

    __slots__ = ("_lock", "count", "sum", "min", "max",
                 "_samples", "_stride", "_skip", "_max_samples")

    def __init__(self, max_samples: int = 512) -> None:
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._samples: list[float] = []
        self._stride = 1
        self._skip = 0
        self._max_samples = max_samples

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            self._skip += 1
            if self._skip >= self._stride:
                self._skip = 0
                self._samples.append(v)
                if len(self._samples) >= self._max_samples:
                    self._samples = self._samples[::2]
                    self._stride *= 2

    def quantile(self, q: float) -> float | None:
        with self._lock:
            if not self._samples:
                return None
            s = sorted(self._samples)
            i = min(len(s) - 1, max(0, round(q * (len(s) - 1))))
            return s[i]

    def summary(self) -> dict:
        """JSON-ready: count/sum/mean/min/max + p50/p90/p99."""
        with self._lock:
            samples = sorted(self._samples)
            count, total = self.count, self.sum
            lo, hi = self.min, self.max

        def pick(q):
            if not samples:
                return None
            return samples[min(len(samples) - 1, max(0, round(q * (len(samples) - 1))))]

        return {
            "count": count,
            "sum": round(total, 6),
            "mean": round(total / count, 6) if count else None,
            "min": lo,
            "max": hi,
            "p50": pick(0.50),
            "p90": pick(0.90),
            "p99": pick(0.99),
        }


#: Operator-facing series every snapshot must carry, observed or not —
#: the metric name catalog's "always present" column (docs/observability.md).
STANDARD_COUNTERS = (
    "worker.matches_rated_total",
    "worker.batches_ok_total",
    "worker.batches_failed_total",
    "worker.dead_letters_total",
    "worker.acks_total",
    "worker.pipeline_degradations_total",
    "worker.pipeline_engine_failures_total",
    "sched.pad_steps_total",
    "sched.pad_slots_total",
    "sched.steps_total",
    # The prefetching device feed (sched/feed.py): starved = the consumer
    # outran the feed (host-bound), backpressure = the feed outran the
    # device (healthy). Pre-declared so "feed never starved" reads as 0,
    # not as a missing series.
    "feed.starved_total",
    "feed.backpressure_total",
    # The fused window kernel's feed (sched/residency.py plans staged by
    # sched/feed.py): windows dispatched, VMEM-budget window cuts, the
    # per-step scatter rows fusion eliminated, and the inert padding
    # steps spills/tails cost. Pre-declared so "never spilled" reads 0.
    "fused.windows_total",
    "fused.spills_total",
    "fused.writebacks_avoided_total",
    "fused.pad_steps_total",
    # The tiered ratings table (sched/tier.py): touched-row hits against
    # the HBM hot set vs misses that promoted from the host cold tier,
    # LRU demotions, the dirty subset written back D2H, and window
    # splits forced by a hot set smaller than one window's touched rows.
    # Pre-declared so an untiered run reads 0, not missing.
    "tier.hits_total",
    "tier.misses_total",
    "tier.promotions_total",
    "tier.demotions_total",
    "tier.dirty_writebacks_total",
    "tier.spills_total",
    "mesh.put_bytes_total",
    "mesh.puts_total",
    # Residency reuse measured on the mesh feed's per-shard compacted
    # row lists: scatter rows a per-shard fused working set would have
    # saved (parallel/mesh.py — accounting now, kernel later).
    "mesh.writebacks_avoidable_total",
    "jax.retraces_total",
    "jax.backend_compiles_total",
    "obs.flight_dumps_total",
    # Series the registry REFUSED to create because a label family hit
    # its cardinality cap (MAX_LABEL_VALUES): the canary for a label
    # minted from an unbounded value (queue names, player ids).
    "obs.dropped_series_total",
    "serve.queries_total",
    "serve.view_publishes_total",
    # The query engine's per-version result caches (serve/engine.py).
    "serve.leaderboard_cache_hits_total",
    "serve.tier_cache_hits_total",
    # The sharded serve plane (serve/view.py + serve/engine.py): H2D
    # bytes the publish path moved (the patch-vs-rebuild pin), routed
    # per-shard query traffic (per-shard serve.shard.queries_total
    # {shard=} series appear on first sample; the base is pre-declared),
    # and the distributed top-k's host merges + candidate volume.
    # Pre-declared so a single-device plane reads 0, not missing.
    "serve.view_publish_bytes_total",
    "serve.shard.queries_total",
    "serve.shard.merges_total",
    "serve.shard.merge_candidates_total",
    # The closed-loop soak harness (analyzer_tpu/loadgen): virtual
    # ticks executed, matchmade matches pushed onto the analyze queue,
    # serve queries issued by the load workload, and SLO-gate failures.
    # Pre-declared so "no soak ran" reads 0, not missing.
    "soak.ticks_total",
    "soak.matches_published_total",
    "soak.queries_sent_total",
    "soak.slo_violations_total",
    # The wire-speed ingest plane (io/ingest.py + sched/feed.py arena,
    # docs/ingest.md): columnar windows decoded (bytes/rows/windows),
    # streams the fast path refused (quoted grammar / no native
    # scanner), arena slab allocations vs freelist reuses (their ratio
    # is the benchdiff-gated hit rate), and H2D commits off the arena.
    "ingest.bytes_decoded_total",
    "ingest.rows_decoded_total",
    "ingest.windows_total",
    "ingest.fallbacks_total",
    "ingest.arena_allocs_total",
    "ingest.arena_reuses_total",
    "ingest.h2d_commits_total",
    # The partitioned broker's priority lanes (service/broker.py):
    # backfill messages admitted behind live traffic, and messages the
    # admission controller held back for host headroom.
    "broker.backfill_admitted_total",
    "broker.backfill_throttled_total",
    # The live SLO plane (obs/history.py + obs/slo.py + obs/audit.py,
    # docs/observability.md): history-ring samples taken, SLO burn
    # onsets and recoveries seen by the watchdog, and the shadow
    # audit's sampled / oracle-replayed / DIVERGED query counts —
    # audit.mismatches_total is the zero-tolerance objective
    # (zero-audit-mismatches): one increment is a correctness incident.
    "history.samples_total",
    "slo.burns_total",
    "slo.recoveries_total",
    "audit.sampled_total",
    "audit.checked_total",
    "audit.mismatches_total",
    # The zero-downtime migration engine (analyzer_tpu/migrate,
    # docs/migration.md): supersteps/windows/matches the backfill
    # dispatched (migrate.steps_total feeds the /statusz ETA through
    # the history rings), dispatch pauses the admission controller
    # imposed for live headroom, engine fall-backs to the non-streamed
    # path (the benchdiff migrate family's vanished-block gate), resumed
    # runs, and atomic lineage cutovers (mirrored by the serve-plane
    # counter below). Pre-declared so "no migration ran" reads 0.
    "migrate.steps_total",
    "migrate.windows_total",
    "migrate.matches_total",
    # Matches the streaming front half ASSIGNED (native or python —
    # migrate.assign_native says which route; docs/migration.md "Native
    # front half"). Leads matches_total during a run: assignment runs
    # ahead of dispatch by the feed ring's depth.
    "migrate.assign_matches_total",
    "migrate.throttled_total",
    "migrate.fallbacks_total",
    "migrate.resumes_total",
    "migrate.cutovers_total",
    # Dual-lineage cutovers performed by the serve plane (serve/view.py
    # cutover_from — the designated entry graftlint GL033 pins).
    "serve.view_cutovers_total",
    # The fleet observability plane (obs/federate.py, docs/
    # observability.md "Fleet plane"): Collector scrape rounds, per-host
    # scrape failures, fleet-scope SLO burn onsets/recoveries over the
    # merged rings, and flight dumps the Collector requested from a
    # burning host via its /debug/flight trigger. Pre-declared so a
    # collector that never saw a burn reads 0, not missing.
    "fleet.scrapes_total",
    "fleet.scrape_errors_total",
    "fleet.burns_total",
    "fleet.recoveries_total",
    "fleet.flight_requests_total",
    # Profile intelligence (obs/profview.py): capture dirs whose device
    # trace parsed end-to-end. Pre-declared so a host that never
    # attributed a capture reads 0, and a candidate whose parser broke
    # reads a vanished delta in benchdiff, not a missing series.
    "profile.captures_parsed_total",
    # The rating-quality plane (obs/quality.py, docs/observability.md
    # "Rating quality"): matches scored against their pre-update
    # predicted win probability, plus the streaming Brier/log-loss sums
    # and the per-bin reliability counts. COUNTERS by design: they sum,
    # so the fleet merge stays exact and the live calibration-floor
    # objective computes an exact windowed ECE from history-ring deltas
    # (quality.bin_count{bin=} / bin_p_sum{bin=} / bin_y_sum{bin=}
    # labeled series appear on first score). Pre-declared so "nothing
    # scored" reads 0, not missing.
    "quality.matches_scored_total",
    "quality.brier_sum",
    "quality.logloss_sum",
    "quality.bin_count",
    "quality.bin_p_sum",
    "quality.bin_y_sum",
    # The multi-host rate fabric (analyzer_tpu/fabric, docs/fabric.md):
    # version-vector observations recorded by the host-local directory,
    # queries the router sent to peer hosts, and routed calls that
    # failed transport (the peer is marked down and leaves the merge).
    # Follower view adoptions (serve/view.py adopt_view — the fabric's
    # by-reference read-replica path) ride the serve.* family.
    # Pre-declared so a single-host deployment reads 0, not missing.
    "fabric.version_observations_total",
    "fabric.remote_lookups_total",
    "fabric.remote_errors_total",
    "serve.view_adoptions_total",
    # The serve front door (serve/frontdoor.py, docs/serving.md "Front
    # door"): requests answered across all reader loops, response bytes
    # rendered (native codec + counted python fallbacks — a nonzero
    # fallback count flips the bench block's native flag), and
    # keep-alive connection reuses saved by the pooled HTTP client
    # (obs/httpd.py PooledHTTPClient — the client half of the same
    # story). Pre-declared so a RoutedHTTPServer-only process reads 0.
    "frontdoor.requests_total",
    "frontdoor.encode_bytes_total",
    "frontdoor.codec_fallbacks_total",
    "frontdoor.pool_reuse_total",
)
STANDARD_GAUGES = (
    "worker.pipeline_lag",
    "worker.pipeline_degraded",
    "worker.pipeline_inflight",
    "worker.matches_per_sec",
    "sched.occupancy",
    # Slab-ring occupancy of the prefetching device feed after the last
    # put/get (sched/feed.py): steady 0 on a busy run = host-bound.
    "feed.depth",
    # Fused working-set high-water mark in table rows (the VMEM budget's
    # denominator, sched/residency.py).
    "fused.working_set_rows",
    # The tiered table's two budget gauges, arbitrated against the
    # device.hbm_bytes_* series: the hot-set capacity in rows
    # (pow2-bucketed from hot_rows) and the cold tier's committed host
    # bytes (sampled by obs/devicemem.py next to the HBM gauges).
    "tier.hot_rows",
    "tier.host_bytes",
    # Per-device series (device.hbm_bytes_in_use{device=...}) appear on
    # first sample; the process total is pre-declared.
    "device.live_buffers",
    # The serving plane (serve/view.py, serve/engine.py): 0 until the
    # first publish — a scraper can tell "no read plane" from "broken".
    "serve.view_version",
    "serve.view_age_seconds",
    # Shard count of the sharded serve plane (0 = single-device).
    "serve.shards",
    # Broker backpressure: ready messages on the consume queue, sampled
    # (throttled) in Worker.poll; per-queue series
    # broker.queue_depth{queue=...} appear on first sample.
    "broker.queue_depth",
    # Soak harness gauges: the configured match rate and how far the
    # virtual clock has advanced (loadgen/driver.py).
    "soak.qps_target",
    "soak.virtual_seconds",
    # The ingest staging arena's resident bytes (sched/feed.py
    # PinnedArena — decode slabs + the tiered table's cold tier).
    "ingest.arena_bytes",
    # Partition count of the partitioned broker (1 = single queue);
    # per-partition broker.queue_depth{queue=,partition=,lane=} series
    # appear on first sample, bounded by the label-cardinality cap.
    "broker.partitions",
    # The live SLO plane: series the history sampler tracks, objectives
    # currently burning (0 = healthy), per-objective burn state
    # (slo.state{objective=} series appear on first transition), and
    # the shadow audit's pending replay backlog.
    "history.series",
    "slo.burning",
    "slo.state",
    "audit.backlog",
    # The migration engine's live progress (analyzer_tpu/migrate):
    # whether a backfill is running, its dispatched-superstep watermark,
    # and the total once the assigner finished (0 until known) — the
    # /statusz progress-% pair.
    "migrate.active",
    "migrate.watermark_steps",
    "migrate.total_steps",
    # 1 while the backfill's first-fit runs on the GIL-released native
    # windowed loop (sched/packer.cc assign_ff_*), 0 on the python
    # fallback — the benchdiff migrate family's assign-native gate
    # catches a capture that silently lost this.
    "migrate.assign_native",
    # The fleet plane's topology gauges (obs/federate.py): scraped
    # targets, targets refused past the host cap, objectives currently
    # burning at FLEET scope, and the fleet history's tracked series.
    # Per-host fleet.host_up{host=} series appear on first scrape.
    "fleet.hosts",
    "fleet.hosts_dropped",
    "fleet.host_up",
    "fleet.burning",
    "fleet.series",
    # Device-idle fraction of the most recently attributed capture
    # window (obs/profview.py): the roofline ledger's batching signal —
    # high idle inside the window = dispatches too small to amortize
    # launch latency.
    "profile.device_idle_frac",
    # The rating-quality plane's derived running means (scrape-page
    # conveniences — the counters above are the source of truth) and
    # the population-drift PSI against the pinned reference window.
    "quality.brier",
    "quality.ece",
    "quality.psi_mu",
    # The fabric's topology gauges (analyzer_tpu/fabric): fleet host
    # count from the directory's topology, this process's host index,
    # and how many shards it owns (0/absent on a non-fabric worker —
    # fabric.host_index/owned_shards are set by the fabric host wiring).
    "fabric.hosts",
    "fabric.host_index",
    "fabric.owned_shards",
    # Open sockets across the front door's reader loops: the /statusz
    # saturation signal (docs/OPERATIONS.md "Diagnosing a saturated
    # front door").
    "frontdoor.connections",
)

#: Histogram families the runtime emits (graftlint GL030 resolves
#: literal ``histogram("...")`` names in service/sched/serve against
#: this list; labeled series like ``phase_seconds{phase=}`` count as
#: one family).
STANDARD_HISTOGRAMS = (
    "phase_seconds",
    "sched.pack_occupancy",
    "serve.microbatch_occupancy",
    "jax.backend_compile_seconds",
    "jax.trace_seconds",
    # Routed cross-host query latency (fabric/route.py, per-peer series
    # fabric.remote_lookup_ms{peer=} — observed on the CALLER's injected
    # clock, so a soak's virtual milliseconds are what land here).
    "fabric.remote_lookup_ms",
)

#: The span/instant name catalog: every runtime-emitted trace-event name
#: (docs/observability.md "Span format"). graftlint GL030 resolves
#: string-literal ``.span("...")`` / ``.instant("...")`` names in
#: service/, sched/ and serve/ against this tuple — a typo'd span name
#: would otherwise just vanish from every timeline, silently. Computed
#: names (``f"phase.{name}"``) are out of scope by design.
SPAN_CATALOG = (
    # worker / pipeline batch lifecycle
    "batch.lifecycle",
    "batch.encode",
    "batch.pack",
    "batch.chain",
    "batch.dispatch",
    "batch.compute",
    "batch.fetch",
    "batch.write_back",
    "batch.commit",
    # the prefetching device feed (producer thread)
    "feed.materialize",
    "feed.transfer",
    # the tiered table's promotion/demotion traffic
    "tier.promote",
    "tier.demote",
    # worker instants
    "worker.dead_letter",
    "worker.pipeline_degraded",
    # causal tracing (obs/tracectx.py): enqueue anchor, batch join,
    # serve-visible publish
    "trace.enqueue",
    "batch.assemble",
    "view.publish",
    # the wire-speed ingest plane: one columnar window's decode into an
    # arena slab, and its H2D commit off that slab (docs/ingest.md)
    "ingest.decode",
    "ingest.commit",
    # the migration engine's front-half thread: one decode window's
    # incremental first-fit feed (native windowed loop or the python
    # recurrence — docs/migration.md "Native front half")
    "migrate.assign",
)

#: Distinct labeled series allowed per family (base metric name) before
#: the registry refuses to mint more. An unbounded label value (player
#: ids, per-request tokens) would otherwise grow the registry — and
#: every snapshot, scrape and flight dump serializing it — forever.
MAX_LABEL_VALUES = 256

#: Label KEYS reserved for the fleet observability plane
#: (obs/federate.py): the Collector merges every scraped worker's
#: series into the fleet registry under ``host=<target>``, so a worker
#: minting its own ``host=``/``fleet=`` label would collide with (or
#: spoof) the federated view. graftlint GL034 flags any
#: counter()/gauge()/histogram() call site outside obs/federate.py
#: passing one of these keys.
RESERVED_LABELS = ("host", "fleet")

#: Operator-facing help text per schema family — the ``# HELP`` line of
#: the Prometheus exposition (docs/observability.md carries the long
#: form; these are the one-line scrape-page versions). Families not
#: listed here (runtime-minted, tests) fall back to a generic line via
#: :func:`schema_help`.
SCHEMA_HELP = {
    "worker.matches_rated_total": "matches rated and committed by the worker",
    "worker.batches_ok_total": "batches that rated and committed cleanly",
    "worker.batches_failed_total": "batches that hit the failure policy",
    "worker.dead_letters_total": "messages dead-lettered to the failed queue",
    "worker.acks_total": "messages acked after a committed batch",
    "worker.pipeline_degradations_total":
        "permanent fallbacks from the pipelined to the sequential loop",
    "worker.pipeline_engine_failures_total":
        "transient pipelined-engine construction failures (retried)",
    "worker.pipeline_lag": "commit lag (batches) of the pipelined engine",
    "worker.pipeline_degraded": "1 while the sequential fallback is active",
    "worker.pipeline_inflight": "pipelined batches submitted, not harvested",
    "worker.matches_per_sec": "worker throughput since start",
    "sched.pad_steps_total": "schedule steps added as padding",
    "sched.pad_slots_total": "schedule slots filled with the pad row",
    "sched.steps_total": "supersteps dispatched by the scan runners",
    "sched.occupancy": "fraction of schedule slots carrying real matches",
    "feed.starved_total": "consumer waits on an empty prefetch ring",
    "feed.backpressure_total": "producer waits on a full prefetch ring",
    "feed.depth": "prefetch-ring occupancy after the last put/get",
    "fused.windows_total": "fused working-set windows dispatched",
    "fused.spills_total": "VMEM-budget window cuts (bulk spills)",
    "fused.writebacks_avoided_total":
        "per-step scatter rows the fused window kernel eliminated",
    "fused.pad_steps_total": "inert padding steps in fused windows",
    "fused.working_set_rows": "fused working-set high-water mark (rows)",
    "tier.hits_total": "touched rows found in the HBM hot set",
    "tier.misses_total": "touched rows promoted from the host cold tier",
    "tier.promotions_total": "cold-to-hot row promotions",
    "tier.demotions_total": "hot-set LRU demotions",
    "tier.dirty_writebacks_total": "dirty rows written back to the cold tier",
    "tier.spills_total": "window cuts forced by an over-budget working set",
    "tier.hot_rows": "hot-set capacity in table rows",
    "tier.host_bytes": "cold tier's committed host bytes",
    "mesh.put_bytes_total": "bytes moved by mesh global puts",
    "mesh.puts_total": "mesh global put calls",
    "mesh.writebacks_avoidable_total":
        "scatter rows a per-shard fused working set would have saved",
    "jax.retraces_total": "XLA retraces observed by the jit listeners",
    "jax.backend_compiles_total": "XLA backend compilations",
    "obs.flight_dumps_total": "flight-recorder artifact dumps written",
    "obs.dropped_series_total":
        "series mints refused by the label-cardinality cap",
    "serve.queries_total": "queries answered by the serving plane",
    "serve.view_publishes_total": "ratings-view versions published",
    "serve.leaderboard_cache_hits_total":
        "leaderboard answers served from the version-keyed cache",
    "serve.tier_cache_hits_total":
        "tier-histogram answers served from the version-keyed cache",
    "serve.view_publish_bytes_total": "H2D bytes moved by view publishes",
    "serve.shard.queries_total": "queries routed to per-shard microbatches",
    "serve.shard.merges_total": "cross-shard top-k host merges",
    "serve.shard.merge_candidates_total": "candidates fed into shard merges",
    "serve.view_cutovers_total": "atomic dual-lineage view cutovers",
    "serve.view_version": "current served view version",
    "serve.view_age_seconds": "seconds since the current view published",
    "serve.shards": "shard count of the serving plane (0 = single)",
    "frontdoor.connections": "open sockets across the front door readers",
    "frontdoor.requests_total": "requests answered by the front door",
    "frontdoor.encode_bytes_total": "response bytes rendered by the codec",
    "frontdoor.codec_fallbacks_total":
        "responses the native codec routed to the python encoder",
    "frontdoor.pool_reuse_total":
        "keep-alive connection reuses by the pooled HTTP client",
    "soak.ticks_total": "soak virtual ticks executed",
    "soak.matches_published_total": "matchmade matches pushed to the queue",
    "soak.queries_sent_total": "serve queries issued by the soak workload",
    "soak.slo_violations_total": "soak SLO gate failures",
    "soak.qps_target": "configured soak match rate",
    "soak.virtual_seconds": "virtual clock position of the running soak",
    "broker.queue_depth": "ready messages on the consume queue",
    "broker.partitions": "partition count of the partitioned broker",
    "broker.backfill_admitted_total":
        "backfill messages admitted behind live traffic",
    "broker.backfill_throttled_total":
        "backfill messages held back for host headroom",
    "ingest.bytes_decoded_total": "bytes decoded by the columnar windows",
    "ingest.rows_decoded_total": "rows decoded by the columnar windows",
    "ingest.windows_total": "columnar decode windows completed",
    "ingest.fallbacks_total": "streams refused by the native fast path",
    "ingest.arena_allocs_total": "pinned-arena slab allocations",
    "ingest.arena_reuses_total": "pinned-arena freelist reuses",
    "ingest.h2d_commits_total": "H2D commits staged off the arena",
    "ingest.arena_bytes": "pinned staging arena resident bytes",
    "device.live_buffers": "live device buffers (leak canary)",
    "history.samples_total": "history-ring sampling rounds",
    "history.series": "series tracked by the history sampler",
    "slo.burns_total": "SLO burn onsets seen by the watchdog",
    "slo.recoveries_total": "SLO burn recoveries",
    "slo.burning": "objectives currently burning (0 = healthy)",
    "slo.state": "per-objective burn state (1 = burning)",
    "audit.sampled_total": "served responses sampled by the shadow audit",
    "audit.checked_total": "sampled responses replayed through the oracle",
    "audit.mismatches_total":
        "served responses that DIVERGED from the bit-exact oracle (SLO: 0)",
    "audit.backlog": "sampled responses awaiting oracle replay",
    "migrate.steps_total": "backfill supersteps dispatched",
    "migrate.windows_total": "backfill decode windows consumed",
    "migrate.matches_total": "matches re-rated by the backfill",
    "migrate.assign_matches_total":
        "matches assigned by the streaming front half's first-fit",
    "migrate.throttled_total": "backfill dispatch pauses for live headroom",
    "migrate.fallbacks_total": "backfills that fell back to the offline path",
    "migrate.resumes_total": "backfills resumed from a checkpoint",
    "migrate.cutovers_total": "migrations that completed their cutover",
    "migrate.active": "1 while a backfill is running",
    "migrate.watermark_steps": "backfill's dispatched-superstep watermark",
    "migrate.total_steps": "backfill's total supersteps once known",
    "migrate.assign_native":
        "1 while the backfill's first-fit runs GIL-released in native code",
    "fleet.scrapes_total": "Collector scrape rounds across the fleet",
    "fleet.scrape_errors_total": "per-host scrape failures",
    "fleet.burns_total": "fleet-scope SLO burn onsets",
    "fleet.recoveries_total": "fleet-scope SLO burn recoveries",
    "fleet.flight_requests_total":
        "flight dumps requested from burning hosts via /debug/flight",
    "fleet.hosts": "targets the Collector scrapes",
    "fleet.hosts_dropped": "targets refused past the fleet host cap",
    "fleet.host_up": "1 while the host's last scrape succeeded",
    "fleet.burning": "objectives burning at fleet scope",
    "fleet.series": "series tracked by the fleet history rings",
    "profile.captures_parsed_total":
        "device-profile capture dirs attributed end-to-end",
    "profile.device_idle_frac":
        "device-idle fraction of the last attributed capture window",
    "quality.matches_scored_total":
        "rated matches scored against their pre-update win probability",
    "quality.brier_sum": "running Brier-score sum over scored matches",
    "quality.logloss_sum": "running log-loss sum over scored matches",
    "quality.bin_count": "scored matches per reliability bin",
    "quality.bin_p_sum": "predicted-probability sum per reliability bin",
    "quality.bin_y_sum": "realized-outcome sum per reliability bin",
    "quality.brier": "running mean Brier score (lower = better)",
    "quality.ece": "running expected calibration error (lower = better)",
    "quality.psi_mu":
        "population-stability index of mu vs the pinned reference window",
    "fabric.version_observations_total":
        "per-host view versions recorded into the fabric directory",
    "fabric.remote_lookups_total": "queries routed to peer fabric hosts",
    "fabric.remote_errors_total":
        "routed fabric calls that failed transport (peer marked down)",
    "serve.view_adoptions_total":
        "leader views adopted by reference into a follower lineage",
    "fabric.hosts": "host count of the fabric topology",
    "fabric.host_index": "this process's fabric host index",
    "fabric.owned_shards": "shards this fabric host owns",
    "phase_seconds": "wall seconds per instrumented phase",
    "sched.pack_occupancy": "per-schedule slot occupancy distribution",
    "serve.microbatch_occupancy": "per-tick serve microbatch fill",
    "jax.backend_compile_seconds": "XLA backend compile durations",
    "jax.trace_seconds": "XLA trace durations",
    "fabric.remote_lookup_ms":
        "routed cross-host query latency (caller-clock milliseconds)",
}


def schema_help(name: str) -> str:
    """The ``# HELP`` line body for a series family; a generic pointer
    at the catalog for names outside :data:`SCHEMA_HELP`."""
    return SCHEMA_HELP.get(
        name, f"analyzer_tpu series {name} (docs/observability.md catalog)"
    )


class MetricsRegistry:
    """get-or-create instrument store keyed by ``name{labels}``.

    Label cardinality is CAPPED per family (:data:`MAX_LABEL_VALUES`
    distinct labeled series per base name): past the cap, the registry
    stops minting new series — the overflow traffic lands on one shared
    unregistered instrument per family (call sites keep working, the
    snapshot stops growing) and every refused mint counts into
    ``obs.dropped_series_total``, so the condition is visible instead
    of an unbounded-memory failure mode."""

    def __init__(
        self,
        declare_standard: bool = True,
        max_label_values: int = MAX_LABEL_VALUES,
    ) -> None:
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self.max_label_values = int(max_label_values)
        # family name -> count of labeled series minted under it.
        self._family_counts: dict[str, int] = {}
        # family name -> the shared post-cap overflow instrument (NOT in
        # the snapshot dicts — it absorbs writes, it is not a series).
        self._overflow: dict[str, object] = {}
        # Created directly (the lock is not re-entrant) and always
        # present: the drop path below increments it under the lock.
        self._dropped = self._counters.setdefault(
            "obs.dropped_series_total", Counter()
        )
        if declare_standard:
            for name in STANDARD_COUNTERS:
                self.counter(name)
            for name in STANDARD_GAUGES:
                self.gauge(name)

    def _get_or_create(self, store: dict, name: str, labels: dict, factory):
        key = _series_key(name, labels)
        with self._lock:
            inst = store.get(key)
            if inst is None:
                if labels:
                    n = self._family_counts.get(name, 0)
                    if n >= self.max_label_values:
                        # Cap hit: count the refusal, route the caller to
                        # the family's shared overflow instrument.
                        self._dropped.add(1)
                        okey = f"{factory.__name__}:{name}"
                        inst = self._overflow.get(okey)
                        if inst is None:
                            inst = self._overflow[okey] = factory()
                        return inst
                    self._family_counts[name] = n + 1
                inst = store[key] = factory()
            return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get_or_create(self._counters, name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get_or_create(self._gauges, name, labels, Gauge)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get_or_create(self._histograms, name, labels, Histogram)

    def snapshot(self) -> dict:
        """JSON-ready view of every series: counter values, gauge values,
        histogram summaries."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: c.value for k, c in sorted(counters.items())},
            "gauges": {k: g.value for k, g in sorted(gauges.items())},
            "histograms": {
                k: h.summary() for k, h in sorted(histograms.items())
            },
        }


_registry_lock = threading.Lock()
_registry: MetricsRegistry | None = None


def get_registry() -> MetricsRegistry:
    """The process-wide registry (created on first use)."""
    global _registry
    with _registry_lock:
        if _registry is None:
            _registry = MetricsRegistry()
        return _registry


def reset_registry() -> MetricsRegistry:
    """Replaces the process-wide registry with a fresh one (tests)."""
    global _registry
    with _registry_lock:
        _registry = MetricsRegistry()
        return _registry
