"""Profile attribution: reads the capture dirs obs/prof.py writes.

``DeviceProfiler.maybe_capture`` wraps one dispatch window in a
``jax.profiler`` trace; until this module, the result was an opaque
TensorBoard directory no repo tool ever read. :func:`analyze_capture`
turns one capture into numbers the rest of the plane can join against:

  * finds the Chrome-format device trace(s) (``*trace.json.gz`` — the
    artifact jax.profiler writes under ``plugins/profile/<run>/``), and
    TOLERATES a missing or torn file: the attribution reports
    ``parsed: false`` with the error instead of crashing the CLI or the
    SLO-violation log path that consumes it;
  * bins device ops into a per-kernel device-time table (sorted by
    total time — ``dominant_kernel`` is the first answer to "is the rig
    run decode-, H2D-, or scan-bound");
  * splits compile-vs-execute (host-side ``*compile*`` events vs device
    busy time) and device-busy-vs-idle over the capture window (merged
    interval union across all device lanes — ``idle_frac`` is the
    roofline ledger's ``device_idle_frac``);
  * joins the capture against the host-side causal-trace forest
    (:func:`decompose_dispatch`): the capture's ``manifest.json`` names
    the batch/trace ids that were in flight, so the host trace's
    ``dispatch`` stage decomposes into device-execute / device-idle /
    host-overhead without filename or clock archaeology.

Clock-injected contract (graftlint **GL046**, same as the history/SLO
plane's GL032): this module never reads a wall clock — every timestamp
it handles was recorded by someone else. Peak-magnitude literals are
banned here too; roofs come from :mod:`analyzer_tpu.obs.hw`.
"""

from __future__ import annotations

import gzip
import json
import os

from analyzer_tpu.obs.registry import get_registry

#: A file is a device trace when its name ends with one of these (jax
#: writes ``<host>.trace.json.gz``; tests may commit a bare
#: ``trace.json``).
_TRACE_SUFFIXES = ("trace.json.gz", "trace.json")

#: Process-name prefixes that classify a trace pid as a DEVICE lane
#: (besides the explicit ``/device:`` marker XLA uses).
_DEVICE_PREFIXES = ("tpu", "gpu")


def find_trace_files(capture_dir: str) -> list[str]:
    """Every Chrome-trace file under a capture dir (sorted relative
    paths, deterministic across runs)."""
    out = []
    for root, _dirs, files in os.walk(capture_dir):
        for fn in files:
            if fn.endswith(_TRACE_SUFFIXES):
                out.append(
                    os.path.relpath(os.path.join(root, fn), capture_dir)
                )
    return sorted(out)


def load_manifest(capture_dir: str) -> dict | None:
    """The capture's ``manifest.json`` (obs/prof.py), or None — older
    captures predate the manifest and still attribute, just without the
    host-trace join keys."""
    try:
        with open(
            os.path.join(capture_dir, "manifest.json"), encoding="utf-8"
        ) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def _read_trace(path: str) -> list[dict]:
    """One Chrome trace file -> its event dicts. Raises on a torn or
    non-trace file; :func:`analyze_capture` catches and reports."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8", errors="replace") as f:
        doc = json.load(f)
    events = doc.get("traceEvents") if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        raise ValueError("no traceEvents array")
    return [e for e in events if isinstance(e, dict)]


def _device_pids(events: list[dict]) -> tuple[set, dict]:
    """(device pids, pid -> process name) from the trace's metadata
    events. A trace with NO process metadata treats every pid as a
    device lane (best-effort: synthetic traces)."""
    names: dict = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            nm = str((e.get("args") or {}).get("name", ""))
            names[e.get("pid")] = nm
    dev = {
        pid for pid, nm in names.items()
        if "/device:" in nm or nm.lower().startswith(_DEVICE_PREFIXES)
    }
    return dev, names


def _merged_busy_us(intervals: list[tuple]) -> float:
    """Total covered time of an interval set (union across lanes: "any
    device lane busy"), so overlapping streams don't double-count."""
    total = 0.0
    end = None
    for start, stop in sorted(intervals):
        if end is None or start > end:
            total += stop - start
            end = stop
        elif stop > end:
            total += stop - end
            end = stop
    return total


def analyze_capture(capture_dir: str, update_metrics: bool = True) -> dict:
    """One capture dir -> the attribution dict (see module docstring).
    Never raises on bad input: ``parsed: false`` + ``error`` instead.
    On success, bumps ``profile.captures_parsed_total`` and sets
    ``profile.device_idle_frac`` in the process registry (pass
    ``update_metrics=False`` from pure consumers like the advisor's
    determinism tests)."""
    out = {
        "dir": capture_dir,
        "parsed": False,
        "error": None,
        "trace_files": [],
        "manifest": None,
        "kernels": [],
        "dominant_kernel": None,
        "device": None,
        "compile": None,
    }
    if not os.path.isdir(capture_dir):
        out["error"] = "no such capture directory"
        return out
    out["manifest"] = load_manifest(capture_dir)
    rels = find_trace_files(capture_dir)
    out["trace_files"] = rels
    if not rels:
        out["error"] = "no trace.json(.gz) under the capture directory"
        return out
    events: list[dict] = []
    errors = []
    for rel in rels:
        try:
            events.extend(_read_trace(os.path.join(capture_dir, rel)))
        except (OSError, EOFError, ValueError) as err:
            errors.append(f"{rel}: {err}")
    if errors:
        out["error"] = "; ".join(errors)
    if not events:
        return out  # every trace file was torn/empty: parsed stays False

    dev_pids, pnames = _device_pids(events)
    treat_all_as_device = not pnames
    kernels: dict[str, list] = {}
    busy_iv: list[tuple] = []
    lanes = set()
    t_min = t_max = None
    compile_us = 0.0
    for e in events:
        if e.get("ph") != "X":
            continue
        try:
            ts = float(e["ts"])
            dur = float(e.get("dur", 0.0))
        except (KeyError, TypeError, ValueError):
            continue
        name = str(e.get("name", "?"))
        is_device = treat_all_as_device or e.get("pid") in dev_pids
        if not is_device:
            # Host side: only the compile split cares (XlaCompile &co).
            if "compile" in name.lower():
                compile_us += dur
            continue
        k = kernels.setdefault(name, [0, 0.0])
        k[0] += 1
        k[1] += dur
        busy_iv.append((ts, ts + dur))
        lanes.add((e.get("pid"), e.get("tid")))
        t_min = ts if t_min is None else min(t_min, ts)
        t_max = ts + dur if t_max is None else max(t_max, ts + dur)

    busy_us = _merged_busy_us(busy_iv)
    window_us = (t_max - t_min) if busy_iv else 0.0
    idle_us = max(0.0, window_us - busy_us)
    idle_frac = idle_us / window_us if window_us > 0 else 0.0
    kern_total = sum(v[1] for v in kernels.values())
    table = [
        {
            "name": name,
            "count": count,
            "total_us": round(total, 3),
            "share": round(total / kern_total, 4) if kern_total > 0 else None,
        }
        for name, (count, total) in sorted(
            kernels.items(), key=lambda kv: (-kv[1][1], kv[0])
        )
    ]
    out["kernels"] = table
    out["dominant_kernel"] = table[0]["name"] if table else None
    out["device"] = {
        "busy_us": round(busy_us, 3),
        "idle_us": round(idle_us, 3),
        "window_us": round(window_us, 3),
        "idle_frac": round(idle_frac, 4),
        "lanes": len(lanes),
    }
    exec_us = busy_us
    out["compile"] = {
        "compile_us": round(compile_us, 3),
        "execute_us": round(exec_us, 3),
        "compile_frac": (
            round(compile_us / (compile_us + exec_us), 4)
            if (compile_us + exec_us) > 0 else None
        ),
    }
    out["parsed"] = True
    if update_metrics:
        reg = get_registry()
        reg.counter("profile.captures_parsed_total").add(1)
        reg.gauge("profile.device_idle_frac").set(round(idle_frac, 4))
    return out


def decompose_dispatch(model, attribution: dict) -> dict | None:
    """The payoff join: the host trace's ``dispatch`` stage split into
    device-execute / device-idle / host-overhead using a capture's
    attribution. Batches are selected by the manifest's in-flight
    batch/trace ids (``scope: manifest``); a manifest-less capture
    falls back to every batch in the model (``scope: all_batches`` —
    honest but coarser). None when the attribution didn't parse or the
    model has no batches to join."""
    if not attribution.get("parsed"):
        return None
    device = attribution.get("device") or {}
    man = attribution.get("manifest") or {}
    ids = set(man.get("batches") or man.get("traces") or [])
    # Stitched forests namespace process-local batch ids by host
    # ("worker:b1"); the manifest records the raw id the capturing
    # process knew, so match either form.
    batches = [
        bt for key, bt in sorted(model.batches.items())
        if key in ids or key.split(":", 1)[-1] in ids
    ]
    scope = "manifest"
    if not batches:
        batches = list(model.batches.values())
        scope = "all_batches"
    if not batches:
        return None
    from analyzer_tpu.obs.traceview import batch_report

    dispatch_ms = 0.0
    for bt in batches:
        v = batch_report(bt)["stages_ms"].get("dispatch")
        if v is not None:
            dispatch_ms += v
    # The capture covers the selected dispatch window(s): clip the
    # device split to the host-observed dispatch total, and call the
    # remainder host overhead (enqueue cost, the dev tunnel's latency).
    exec_ms = min(device.get("busy_us", 0.0) / 1e3, dispatch_ms)
    idle_ms = min(device.get("idle_us", 0.0) / 1e3,
                  max(0.0, dispatch_ms - exec_ms))
    host_ms = max(0.0, dispatch_ms - exec_ms - idle_ms)
    out = {
        "scope": scope,
        "batches": sorted(bt.batch_id for bt in batches),
        "dispatch_ms": round(dispatch_ms, 3),
        "device_execute_ms": round(exec_ms, 3),
        "device_idle_ms": round(idle_ms, 3),
        "host_overhead_ms": round(host_ms, 3),
    }
    if dispatch_ms > 0:
        out["shares"] = {
            "device_execute": round(exec_ms / dispatch_ms, 4),
            "device_idle": round(idle_ms / dispatch_ms, 4),
            "host_overhead": round(host_ms / dispatch_ms, 4),
        }
    return out


def render_attribution(att: dict) -> str:
    """Human render of :func:`analyze_capture`'s dict (``cli profile``)."""
    out = [f"profile capture: {att['dir']}"]
    man = att.get("manifest") or {}
    if man:
        wall = ""
        if man.get("wall_start") is not None and man.get("wall_end") is not None:
            wall = f", wall window {man['wall_end'] - man['wall_start']:.3f}s"
        out.append(
            f"  manifest: reason={man.get('reason', '?')}"
            f", platform={(man.get('device') or {}).get('platform') or '?'}"
            f", batches in flight: "
            f"{', '.join(man.get('batches') or []) or '(none)'}{wall}"
        )
    if not att["parsed"]:
        out.append(f"  parsed: false — {att.get('error') or 'no device events'}")
        return "\n".join(out) + "\n"
    dev = att["device"]
    comp = att["compile"]
    out.append(
        f"  device: busy {dev['busy_us'] / 1e3:.3f} ms / idle "
        f"{dev['idle_us'] / 1e3:.3f} ms over a "
        f"{dev['window_us'] / 1e3:.3f} ms window "
        f"(idle {100 * dev['idle_frac']:.1f}%, {dev['lanes']} lane(s))"
    )
    if comp["compile_frac"] is not None:
        out.append(
            f"  compile vs execute: {comp['compile_us'] / 1e3:.3f} ms vs "
            f"{comp['execute_us'] / 1e3:.3f} ms "
            f"({100 * comp['compile_frac']:.1f}% compile)"
        )
    if att["kernels"]:
        out.append("  per-kernel device time:")
        width = max(len(k["name"]) for k in att["kernels"][:12])
        for k in att["kernels"][:12]:
            share = f"{100 * k['share']:5.1f}%" if k["share"] is not None else ""
            out.append(
                f"    {k['name']:<{width}}  {k['total_us'] / 1e3:9.3f} ms  "
                f"x{k['count']:<5d}{share}"
            )
        out.append(f"  dominant kernel: {att['dominant_kernel']}")
    return "\n".join(out) + "\n"


def render_decomposition(decomp: dict) -> str:
    """Human render of :func:`decompose_dispatch`'s dict (the extra
    section under ``cli trace`` / ``cli profile --trace`` reports)."""
    shares = decomp.get("shares") or {}

    def pct(key):
        v = shares.get(key)
        return "" if v is None else f"  {100 * v:5.1f}%"

    return (
        f"dispatch decomposition ({decomp['scope']}; batches "
        f"{', '.join(decomp['batches'])}):\n"
        f"  dispatch total : {decomp['dispatch_ms']:9.3f} ms\n"
        f"  device execute : {decomp['device_execute_ms']:9.3f} ms"
        f"{pct('device_execute')}\n"
        f"  device idle    : {decomp['device_idle_ms']:9.3f} ms"
        f"{pct('device_idle')}\n"
        f"  host overhead  : {decomp['host_overhead_ms']:9.3f} ms"
        f"{pct('host_overhead')}\n"
    )
