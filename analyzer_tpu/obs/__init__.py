"""Unified runtime telemetry: metrics registry, span tracer, retrace
accounting.

The static half of observability is graftlint (``docs/lint.md``): GL001+
flag the *hazards* — host syncs, retrace storms — before they ship. This
package is the runtime half: when a bench run or a degraded worker is
slow, the snapshot says *why* (which phase dominated, which jitted
entrypoint retraced, how much schedule padding burned, how far the
pipeline lagged) instead of just *that* it was slow.

Three stdlib-only cores (importable without jax — the CLI's ``metrics``
subcommand and the lint layer must stay light):

  * :mod:`~analyzer_tpu.obs.registry` — process-wide counters, gauges and
    histograms with quantile summaries, JSON-snapshot and Prometheus-text
    exposition;
  * :mod:`~analyzer_tpu.obs.tracer` — span tracing into a bounded ring,
    exported as Chrome trace-event JSONL (open in Perfetto alongside the
    XLA traces ``utils.trace`` captures);
  * :mod:`~analyzer_tpu.obs.snapshot` — the one-file JSON artifact
    (`cli rate --metrics-out`) joining metrics, spans and retrace counts.

Plus one jax-aware module, :mod:`~analyzer_tpu.obs.retrace`, hooking
``jax.monitoring``'s compile events and tracking named jitted entrypoints
via their ``_cache_size()`` — GL004's retrace hazard as a measurable
runtime counter.

The LIVE half (this PR's obsd plane — everything above is post-hoc):

  * :mod:`~analyzer_tpu.obs.httpd` — the shared route-table HTTP
    plumbing (daemon ``ThreadingHTTPServer``, loopback default) backing
    both obsd and the ratesrv query plane (``analyzer_tpu/serve``);
  * :mod:`~analyzer_tpu.obs.server` — stdlib HTTP endpoints on a thread
    (``/metrics`` ``/healthz`` ``/readyz`` ``/statusz``
    ``/debug/snapshot``) with a pluggable :class:`HealthChecks` registry;
  * :mod:`~analyzer_tpu.obs.flight` — the flight recorder: a bounded ring
    of recent events dumped as a timestamped artifact directory on
    dead-letter / degradation / SIGUSR1;
  * :mod:`~analyzer_tpu.obs.devicemem` — HBM-occupancy and live-buffer
    gauges sampled at batch boundaries (jax-aware, lazy import);
  * :mod:`~analyzer_tpu.obs.benchdiff` — the BENCH_*.json trajectory
    diff behind ``cli benchdiff``;
  * :mod:`~analyzer_tpu.obs.federate` — the FLEET plane: a Collector
    scraping N workers' obsd endpoints into one federated registry
    under the reserved ``host=`` label, fleet-scope SLO burns with
    per-host attribution, and the ``/fleetz`` serving surface
    (``cli fleet``; docs/observability.md "Fleet plane");
  * :mod:`~analyzer_tpu.obs.profview` — profile attribution: reads the
    capture dirs :mod:`~analyzer_tpu.obs.prof` writes into a per-kernel
    device-time table + busy/idle split, and joins the capture against
    the host trace forest (``cli profile``);
  * :mod:`~analyzer_tpu.obs.hw` — the roofline ledger's peak table and
    per-dispatch bytes/flops cost model (the one sanctioned home of
    peak-magnitude literals, graftlint GL046);
  * :mod:`~analyzer_tpu.obs.advisor` — the telemetry-driven tuning
    advisor: a deterministic rule table over the repo's artifacts that
    names the bottleneck and the knob (``cli tune``).

Metric name catalog: docs/observability.md.
"""

from analyzer_tpu.obs.audit import ShadowAuditor
from analyzer_tpu.obs.federate import Collector, FleetServer
from analyzer_tpu.obs.devicemem import (
    maybe_sample as maybe_sample_device_memory,
    sample_device_memory,
)
from analyzer_tpu.obs.history import (
    HistorySampler,
    get_history,
    reset_history,
)
from analyzer_tpu.obs.flight import (
    FlightRecorder,
    get_flight_recorder,
    reset_flight_recorder,
)
from analyzer_tpu.obs.prof import (
    DeviceProfiler,
    get_device_profiler,
    reset_device_profiler,
)
from analyzer_tpu.obs.registry import (
    MetricsRegistry,
    get_registry,
    reset_registry,
)
from analyzer_tpu.obs.retrace import (
    install_jax_hooks,
    jax_hooks_installed,
    retrace_counts,
    track_jit,
)
from analyzer_tpu.obs.snapshot import (
    prometheus_text,
    render_summary,
    snapshot,
    write_chrome_trace,
    write_snapshot,
)
from analyzer_tpu.obs.server import HealthChecks, ObsServer, connectivity_probe
from analyzer_tpu.obs.slo import (
    Objective,
    Watchdog,
    get_watchdog,
    reset_watchdog,
)
from analyzer_tpu.obs.tracectx import (
    TraceContext,
    enable_tracing,
    tracing_enabled,
)
from analyzer_tpu.obs.tracer import (
    Tracer,
    bind_trace,
    current_trace,
    get_tracer,
    instant,
    span,
)

__all__ = [
    "Collector",
    "DeviceProfiler",
    "FleetServer",
    "FlightRecorder",
    "HealthChecks",
    "HistorySampler",
    "MetricsRegistry",
    "Objective",
    "ObsServer",
    "ShadowAuditor",
    "TraceContext",
    "Tracer",
    "Watchdog",
    "bind_trace",
    "connectivity_probe",
    "current_trace",
    "enable_tracing",
    "get_device_profiler",
    "get_flight_recorder",
    "get_history",
    "get_registry",
    "get_tracer",
    "get_watchdog",
    "install_jax_hooks",
    "instant",
    "jax_hooks_installed",
    "maybe_sample_device_memory",
    "prometheus_text",
    "render_summary",
    "reset_device_profiler",
    "reset_flight_recorder",
    "reset_history",
    "reset_registry",
    "reset_watchdog",
    "retrace_counts",
    "sample_device_memory",
    "snapshot",
    "span",
    "tracing_enabled",
    "track_jit",
    "write_chrome_trace",
    "write_snapshot",
]
