"""Flight recorder: a bounded ring of recent events, dumped on failure.

The snapshot artifact answers "what did the whole run look like"; the
flight recorder answers "what happened in the last seconds BEFORE the
failure". It runs always-on and nearly free — log records (via a logging
handler on the package root), batch notes from the worker, and arbitrary
``note()`` breadcrumbs land in one bounded deque — and on a trigger
(dead-letter, pipeline degradation, unhandled batch exception, SIGUSR1)
``dump()`` freezes everything into a timestamped artifact directory:

  ``snapshot.json``   the full metrics snapshot (counters/gauges/
                      histograms/retraces/spans) at dump time;
  ``history.json``    the telemetry history rings (obs/history.py) —
                      the trajectory INTO the incident, not just the
                      moment of it;
  ``trace.jsonl``     the span ring as Chrome trace-event JSONL
                      (Perfetto-loadable — the failure's timeline);
  ``events.log``      the recent-events ring, one JSON object per line,
                      oldest first;
  ``context.json``    reason, wall time, pid/argv/host, loaded jax
                      version, the owner's config (URI-shaped values
                      redacted), and a whitelisted environment capture.

Dumps are throttled (``min_interval_s``) so a dead-letter storm produces
one artifact plus suppressed-dump breadcrumbs, not a disk full of
identical directories; operator-triggered dumps (SIGUSR1) bypass the
throttle with ``force=True``.

Artifacts land under ``base_dir`` — ``ANALYZER_TPU_FLIGHT_DIR`` or the
owner's explicit configuration (``Worker(flight_dir=...)``,
``cli worker --flight-dir``). With NO directory configured the ring still
records but ``dump()`` is a breadcrumbed no-op: library code must never
scatter artifact directories into an unsuspecting cwd.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from collections import deque

from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs.registry import get_registry
from analyzer_tpu.obs.snapshot import write_chrome_trace, write_snapshot

logger = get_logger(__name__)

ENV_DIR = "ANALYZER_TPU_FLIGHT_DIR"

#: Environment prefixes worth capturing in context.json — the knobs that
#: change behavior, not the whole environment (which carries secrets).
_ENV_PREFIXES = (
    "ANALYZER_TPU_", "JAX_", "XLA_", "BENCH_", "PIPELINE",
    "BATCHSIZE", "CHUNKSIZE", "QUEUE", "IDLE_TIMEOUT", "TAU",
    "UNKNOWN_PLAYER_SIGMA", "DOCRUNCH", "DOSEW", "DOTELESUCK",
)
_REDACT_MARKERS = ("uri", "password", "secret", "token", "key")


def _redact(mapping: dict) -> dict:
    """URI/credential-shaped values never reach an artifact a human will
    paste into a ticket."""
    out = {}
    for k, v in mapping.items():
        if any(m in k.lower() for m in _REDACT_MARKERS) and v:
            out[k] = "<redacted>"
        else:
            out[k] = v
    return out


class _LogCapture(logging.Handler):
    """Mirrors package log records into the recorder's ring. Emission
    must never raise into the logging call site."""

    def __init__(self, recorder: "FlightRecorder") -> None:
        super().__init__(level=logging.INFO)
        self._recorder = recorder

    def emit(self, record: logging.LogRecord) -> None:
        try:
            self._recorder.note(
                "log",
                level=record.levelname,
                logger=record.name,
                msg=record.getMessage(),
            )
        except Exception:  # noqa: BLE001 — a telemetry sink must stay silent
            pass


class FlightRecorder:
    def __init__(
        self,
        base_dir: str | None = None,
        max_events: int = 2000,
        min_interval_s: float = 30.0,
        clock=time.monotonic,
    ) -> None:
        self._lock = threading.Lock()
        self._events: deque[dict] = deque(maxlen=max_events)
        self.base_dir = base_dir or os.environ.get(ENV_DIR) or None
        self.min_interval_s = min_interval_s
        self._clock = clock
        # Throttle keyed PER REASON: a dead-letter storm's dump must not
        # suppress a later degradation dump (distinct failure, distinct
        # artifact) — one shared timestamp did exactly that.
        self._last_dump_at: dict[str, float] = {}
        self.dumps = 0
        self._handler: _LogCapture | None = None

    def configure(
        self,
        base_dir: str | None = None,
        min_interval_s: float | None = None,
    ) -> "FlightRecorder":
        """Late configuration of the process-wide recorder (the worker
        owns the directory decision, not import order)."""
        if base_dir is not None:
            self.base_dir = base_dir
        if min_interval_s is not None:
            self.min_interval_s = min_interval_s
        return self

    # -- the ring ---------------------------------------------------------
    def note(self, kind: str, **fields) -> None:
        """One breadcrumb: JSON-scalar fields only (they are serialized
        verbatim into events.log)."""
        event = {"ts": round(time.time(), 3), "kind": kind, **fields}
        with self._lock:
            self._events.append(event)

    def note_batch(self, n_ids: int, matches: int, first_id=None) -> None:
        """The worker's per-batch breadcrumb — the last-N batch sizes and
        a representative id are exactly what a dead-letter page needs."""
        self.note("batch", n_ids=n_ids, matches=matches, first_id=first_id)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    # -- log capture ------------------------------------------------------
    def capture_logs(self) -> None:
        """Attaches the ring to every package logger, present and future
        (idempotent). Package loggers do not propagate, so this goes
        through ``logging_utils.add_shared_handler`` rather than a
        root-level handler that would capture nothing."""
        if self._handler is not None:
            return
        from analyzer_tpu.logging_utils import add_shared_handler

        self._handler = _LogCapture(self)
        add_shared_handler(self._handler)

    def release_logs(self) -> None:
        if self._handler is not None:
            from analyzer_tpu.logging_utils import remove_shared_handler

            remove_shared_handler(self._handler)
        self._handler = None

    # -- the dump ---------------------------------------------------------
    def dump(
        self,
        reason: str,
        config: dict | None = None,
        force: bool = False,
        profile: dict | None = None,
    ) -> str | None:
        """Freezes the current telemetry + ring into an artifact
        directory; returns its path. Returns None (with a breadcrumb)
        when no base_dir is configured or a non-forced dump lands inside
        the throttle window — the window is PER REASON, so a dead-letter
        storm's artifact cannot suppress a later degradation dump.
        ``profile`` (the device profiler's capture info,
        ``obs/prof.py``) rides into context.json so the artifact names
        the jax.profiler capture directory that goes with it. Never
        raises — the callers are failure paths that must finish their
        actual job (dead-lettering, degradation bookkeeping) no matter
        what the disk does."""
        if self.base_dir is None:
            self.note("dump.skipped", reason=reason, why="no base_dir")
            return None
        now = self._clock()
        with self._lock:
            last = self._last_dump_at.get(reason)
            if (
                not force
                and last is not None
                and now - last < self.min_interval_s
            ):
                throttled = True
            else:
                throttled = False
                self._last_dump_at[reason] = now
        if throttled:
            self.note("dump.suppressed", reason=reason)
            return None
        try:
            return self._write(reason, config, profile)
        except Exception as err:  # noqa: BLE001 — failure paths come first
            self.note("dump.failed", reason=reason, error=repr(err))
            logger.exception("flight-recorder dump failed (%s)", reason)
            return None

    def _write(
        self, reason: str, config: dict | None, profile: dict | None = None
    ) -> str:
        stamp = time.strftime("%Y%m%d-%H%M%S")
        safe_reason = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in reason
        )
        base = os.path.join(
            self.base_dir, f"flight-{stamp}-{safe_reason}-{os.getpid()}"
        )
        path = base
        n = 1
        while os.path.exists(path):  # two dumps in one second
            path = f"{base}.{n}"
            n += 1
        os.makedirs(path)
        write_snapshot(os.path.join(path, "snapshot.json"))
        write_chrome_trace(os.path.join(path, "trace.jsonl"))
        # The trajectory INTO the incident (obs/history.py): the
        # snapshot above is the moment, history.json is how the process
        # got there — the first thing a paged operator should plot.
        from analyzer_tpu.obs.history import get_history

        with open(
            os.path.join(path, "history.json"), "w", encoding="utf-8"
        ) as f:
            json.dump(get_history().to_json(), f, indent=1, sort_keys=True)
            f.write("\n")
        with open(
            os.path.join(path, "events.log"), "w", encoding="utf-8"
        ) as f:
            for event in self.events():
                f.write(json.dumps(event) + "\n")
        context = {
            "reason": reason,
            "ts_wall": time.time(),
            "pid": os.getpid(),
            "argv": sys.argv,
            "python": sys.version.split()[0],
            "jax": getattr(sys.modules.get("jax"), "__version__", None),
            "config": _redact(config) if config else None,
            # Device-time attribution: where the jax.profiler capture
            # that pairs with this dump lives (None when no profiler is
            # armed — obs/prof.py, docs/observability.md).
            "profile": profile,
            "env": _redact({
                k: v for k, v in os.environ.items()
                if k.startswith(_ENV_PREFIXES)
            }),
        }
        with open(
            os.path.join(path, "context.json"), "w", encoding="utf-8"
        ) as f:
            json.dump(context, f, indent=1, sort_keys=True)
            f.write("\n")
        self.dumps += 1
        get_registry().counter("obs.flight_dumps_total").add(1)
        self.note("dump", reason=reason, path=path)
        logger.warning("flight recorder dumped to %s (%s)", path, reason)
        return path


_recorder_lock = threading.Lock()
_recorder: FlightRecorder | None = None


def get_flight_recorder() -> FlightRecorder:
    """The process-wide recorder (created on first use, log capture
    armed)."""
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
            _recorder.capture_logs()
        return _recorder


def reset_flight_recorder(**kwargs) -> FlightRecorder:
    """Replaces the process-wide recorder with a fresh one (tests)."""
    global _recorder
    with _recorder_lock:
        if _recorder is not None:
            _recorder.release_logs()
        _recorder = FlightRecorder(**kwargs)
        _recorder.capture_logs()
        return _recorder
