"""Telemetry history rings: a bounded in-process time series per metric.

Every surface the obs package had before this module is POINT-IN-TIME:
``/metrics`` and ``/statusz`` answer "what is the value now", the flight
recorder freezes "the moment of the incident", the snapshot artifact is
one instant of one run. Nothing answered "what was the trajectory INTO
this state" — the question every page starts with. The history sampler
closes that gap without a metrics backend: it periodically records the
registry's counters, gauges and histogram quantiles into fixed-size
rings with tiered downsampling, so a live worker carries its own recent
past (raw samples for the last minutes, 10 s buckets for the last hour,
1 m buckets for the last hours) in bounded memory.

Design constraints, in order:

  * **clock-injected** — the sampler NEVER reads a wall clock
    (graftlint GL032 bans ``time.*`` in this module): every ``sample``
    call takes ``now`` from the caller's clock. The worker drives it
    from ``Worker.clock``, which under the soak is the VirtualClock —
    so history contents are deterministic per (seed, config) and the
    deterministic block is bit-identical with the sampler on or off;
  * **stdlib only** — like the registry it samples, importable without
    jax (``cli history`` renders saved histories offline);
  * **bounded** — ring capacities are fixed at construction; a series
    cap (:data:`MAX_SERIES`) bounds the whole structure against a
    labeled-series explosion the registry's own cardinality cap
    already throttles upstream.

Consumers: ``/historyz`` (JSON series for the scrape window),
``/statusz`` trend sparklines, the flight recorder's ``history.json``
(the trajectory INTO the incident rides every dump), ``cli history``,
and the SLO engine's multi-window burn rates (:mod:`obs.slo`).
"""

from __future__ import annotations

import threading

#: (tier name, bucket seconds, ring capacity). ``raw`` keeps every
#: sample; coarser tiers keep one aggregate per bucket. At a 1 s sample
#: cadence: raw ~8 min, 10s ~1 h, 1m ~4 h of trajectory.
TIERS = (("raw", None, 512), ("10s", 10.0, 360), ("1m", 60.0, 240))

#: Hard cap on tracked series — the registry's per-family label cap
#: bounds growth upstream, this bounds the whole history structure.
MAX_SERIES = 1024

#: Histogram quantiles recorded as series (``<hist>:p99`` etc.).
HIST_QUANTILES = ("p50", "p99")

#: Unicode sparkline ramp for the /statusz + cli history trend render.
SPARK = "▁▂▃▄▅▆▇█"


def _coerce(value) -> float | None:
    """Gauge values may be None/bool/str — record what coerces, skip
    the rest (a string-valued gauge has no trajectory)."""
    if value is None:
        return None
    if isinstance(value, bool):
        return 1.0 if value else 0.0
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


class _Ring:
    """Fixed-capacity append ring of (t, last, min, max) rows. ``raw``
    rings carry last == min == max (one sample); bucketed rings carry
    the bucket aggregate."""

    __slots__ = ("capacity", "_rows", "_start")

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._rows: list = []
        self._start = 0  # index of the oldest row (circular)

    def append(self, row) -> None:
        if len(self._rows) < self.capacity:
            self._rows.append(row)
        else:
            self._rows[self._start] = row
            self._start = (self._start + 1) % self.capacity

    def last(self):
        if not self._rows:
            return None
        return self._rows[(self._start - 1) % len(self._rows)]

    def replace_last(self, row) -> None:
        self._rows[(self._start - 1) % len(self._rows)] = row

    def rows(self) -> list:
        """Oldest-first copy."""
        return self._rows[self._start:] + self._rows[: self._start]

    def __len__(self) -> int:
        return len(self._rows)


class _Series:
    """One metric's tiered rings. ``kind`` is ``counter`` (cumulative,
    deltas meaningful) or ``gauge`` (instantaneous; histogram quantiles
    record as gauges)."""

    __slots__ = ("name", "kind", "rings")

    def __init__(self, name: str, kind: str) -> None:
        self.name = name
        self.kind = kind
        self.rings = {tier: _Ring(cap) for tier, _, cap in TIERS}

    def record(self, t: float, value: float) -> None:
        self.rings["raw"].append((t, value, value, value))
        for tier, bucket_s, _cap in TIERS:
            if bucket_s is None:
                continue
            ring = self.rings[tier]
            bucket_t = (t // bucket_s) * bucket_s
            last = ring.last()
            if last is not None and last[0] == bucket_t:
                ring.replace_last(
                    (bucket_t, value, min(last[2], value),
                     max(last[3], value))
                )
            else:
                ring.append((bucket_t, value, value, value))

    def window_rows(self, window_s: float, now: float) -> list:
        """Oldest-first (t, last, min, max) rows covering
        ``[now - window_s, now]`` from the finest tier whose retained
        span reaches the window start (raw first, then coarser), with
        the last row at/before the window start included as the delta
        baseline. Falls back to the widest partial coverage when no
        tier reaches back far enough (young process)."""
        lo = now - window_s
        widest = None
        for tier, _bucket, _cap in TIERS:
            rows = [r for r in self.rings[tier].rows() if r[0] <= now]
            if not rows:
                continue
            if rows[0][0] <= lo:
                before = [r for r in rows if r[0] < lo]
                in_window = [r for r in rows if r[0] >= lo]
                return (before[-1:] if before else []) + in_window
            if widest is None or rows[0][0] < widest[0][0]:
                widest = rows
        return widest or []


class HistorySampler:
    """The sampler + ring store. One :meth:`sample` call records every
    registry counter/gauge (and configured histogram quantiles) at the
    caller's timestamp. Thread-safe; reads never block sampling for
    long (rings copy out under the lock)."""

    def __init__(self, registry=None, max_series: int = MAX_SERIES) -> None:
        self._registry = registry
        self._lock = threading.Lock()
        self._series: dict[str, _Series] = {}
        self.max_series = int(max_series)
        self.last_sample_t: float | None = None
        self.samples = 0
        # Pre-sample probes (devicemem, tier host bytes): refreshed so
        # the gauges the sampler is about to read are current. Probe
        # failures never reach the sampling path.
        self._probes: list = []

    # -- probes -----------------------------------------------------------
    def add_probe(self, fn) -> None:
        """Registers a nullary callable run before each sample (e.g.
        ``obs.devicemem.maybe_sample`` so HBM/cold-tier gauges are fresh
        in every history row). Idempotent per function object."""
        with self._lock:
            if fn not in self._probes:
                self._probes.append(fn)

    def remove_probe(self, fn) -> None:
        with self._lock:
            if fn in self._probes:
                self._probes.remove(fn)

    # -- sampling ---------------------------------------------------------
    def _get_series(self, name: str, kind: str) -> _Series | None:
        s = self._series.get(name)
        if s is None:
            if len(self._series) >= self.max_series:
                return None
            s = self._series[name] = _Series(name, kind)
        return s

    def sample(self, now: float) -> None:
        """Records one row per live series at timestamp ``now`` (the
        CALLER's clock — the worker's, which under the soak is the
        virtual clock). Monotonically non-decreasing ``now`` expected;
        an equal timestamp overwrites nothing (raw rings just gain a
        duplicate-t row, harmless)."""
        from analyzer_tpu.obs.registry import get_registry

        reg = self._registry or get_registry()
        with self._lock:
            probes = list(self._probes)
        for probe in probes:
            try:
                probe()
            except Exception:  # noqa: BLE001 — a probe must not stop sampling
                pass
        snap = reg.snapshot()
        t = float(now)
        with self._lock:
            for name, value in snap["counters"].items():
                v = _coerce(value)
                if v is None:
                    continue
                s = self._get_series(name, "counter")
                if s is not None:
                    s.record(t, v)
            for name, value in snap["gauges"].items():
                v = _coerce(value)
                if v is None:
                    continue
                s = self._get_series(name, "gauge")
                if s is not None:
                    s.record(t, v)
            for name, summ in snap["histograms"].items():
                for q in HIST_QUANTILES:
                    v = _coerce(summ.get(q))
                    if v is None:
                        continue
                    s = self._get_series(f"{name}:{q}", "gauge")
                    if s is not None:
                        s.record(t, v)
            self.last_sample_t = t
            self.samples += 1
        reg.counter("history.samples_total").add(1)
        reg.gauge("history.series").set(len(self._series))

    # -- queries ----------------------------------------------------------
    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def series(self, name: str, tier: str = "raw") -> list:
        """Oldest-first ``[t, last, min, max]`` rows for ``name`` (empty
        when unknown)."""
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return []
            return [list(r) for r in s.rings[tier].rows()]

    def latest(self, name: str):
        """(t, value) of the newest raw sample, or None."""
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return None
            row = s.rings["raw"].last()
            return None if row is None else (row[0], row[1])

    def window_delta(self, name: str, window_s: float, now: float):
        """Counter delta over ``[now - window_s, now]`` as
        ``(delta, span_s)`` from the finest covering tier, or None when
        fewer than two samples exist. The baseline is the OLDEST sample
        inside the window (counters only grow, so a partially covered
        window under-reports, never over-reports a burn)."""
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return None
            rows = s.window_rows(window_s, now)
        if len(rows) < 2:
            return None
        delta = rows[-1][1] - rows[0][1]
        span = rows[-1][0] - rows[0][0]
        return (delta, span)

    def window_max(self, name: str, window_s: float, now: float):
        """Max observed value over the window (gauges), or None."""
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return None
            rows = s.window_rows(window_s, now)
        if not rows:
            return None
        return max(r[3] for r in rows)

    def window_growth(self, name: str, window_s: float, now: float):
        """(last - first, span_s) over the window — the memory-leak
        burn-rate primitive (can be negative; gauges shrink)."""
        return self.window_delta(name, window_s, now)

    def last_change(self, name: str):
        """(t_of_last_value_change, current_value) over the raw ring —
        e.g. how long ``serve.view_version`` has sat at its value, in
        sampler time. None when unknown or single-valued so far."""
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return None
            rows = s.rings["raw"].rows()
        if not rows:
            return None
        current = rows[-1][1]
        t_change = rows[0][0]
        for t, v, _mn, _mx in reversed(rows):
            if v != current:
                break
            t_change = t
        return (t_change, current)

    # -- exposition -------------------------------------------------------
    def to_json(
        self, prefix: str | None = None, tier: str | None = None
    ) -> dict:
        """The ``/historyz`` / ``history.json`` payload: every series
        (optionally name-prefix filtered) with its rings (optionally one
        tier). Rows are ``[t, last, min, max]``."""
        with self._lock:
            series = {
                name: s for name, s in self._series.items()
                if prefix is None or name.startswith(prefix)
            }
            out = {}
            for name, s in sorted(series.items()):
                rings = {
                    t: [list(r) for r in ring.rows()]
                    for t, ring in s.rings.items()
                    if (tier is None or t == tier) and len(ring)
                }
                out[name] = {"kind": s.kind, "rings": rings}
            return {
                "version": 1,
                "last_sample_t": self.last_sample_t,
                "samples": self.samples,
                "tiers": [[t, b, c] for t, b, c in TIERS],
                "series": out,
            }

    def sparkline(self, name: str, width: int = 32) -> str | None:
        """A unicode trend line of the newest ``width`` raw samples —
        counters as per-sample deltas (activity), gauges as values.
        None when fewer than two samples exist."""
        with self._lock:
            s = self._series.get(name)
            if s is None:
                return None
            rows = s.rings["raw"].rows()[-(width + 1):]
            kind = s.kind
        if len(rows) < 2:
            return None
        if kind == "counter":
            vals = [
                rows[i + 1][1] - rows[i][1] for i in range(len(rows) - 1)
            ]
        else:
            vals = [r[1] for r in rows[-width:]]
        return render_sparkline(vals)


def render_sparkline(vals: list) -> str:
    """Values -> one :data:`SPARK` character each (min..max scaled; a
    flat series renders as all-low, which reads as "quiet")."""
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return SPARK[0] * len(vals)
    scale = (len(SPARK) - 1) / (hi - lo)
    return "".join(SPARK[int((v - lo) * scale)] for v in vals)


def render_history(payload: dict, names=None, tier: str = "raw",
                   width: int = 48) -> str:
    """The human render of a ``to_json`` payload (``cli history``,
    trend sections): one line per series — sparkline, last value, and
    for counters the window delta."""
    series = payload.get("series", {})
    picked = names or sorted(series)
    out = []
    for name in picked:
        s = series.get(name)
        if s is None:
            continue
        rows = (s.get("rings") or {}).get(tier) or []
        if len(rows) < 2:
            continue
        rows = rows[-(width + 1):]
        if s.get("kind") == "counter":
            vals = [rows[i + 1][1] - rows[i][1] for i in range(len(rows) - 1)]
            tail = (
                f"last={rows[-1][1]:g} "
                f"delta={rows[-1][1] - rows[0][1]:+g}"
            )
        else:
            vals = [r[1] for r in rows[-width:]]
            tail = f"last={rows[-1][1]:g} min={min(vals):g} max={max(vals):g}"
        span = rows[-1][0] - rows[0][0]
        out.append(
            f"  {name:<44} {render_sparkline(vals)}  {tail} "
            f"(over {span:g}s)"
        )
    if not out:
        return "  (no series with enough history)\n"
    return "\n".join(out) + "\n"


_history_lock = threading.Lock()
_history: HistorySampler | None = None


def get_history() -> HistorySampler:
    """The process-wide history sampler (created on first use)."""
    global _history
    with _history_lock:
        if _history is None:
            _history = HistorySampler()
        return _history


def reset_history(**kwargs) -> HistorySampler:
    """Replaces the process-wide sampler with a fresh one (tests)."""
    global _history
    with _history_lock:
        _history = HistorySampler(**kwargs)
        return _history
