"""Hardware peak table + the per-dispatch bytes/flops cost model.

The roofline ledger's two inputs live here and ONLY here:

  * **Peak table** — nominal per-chip HBM bandwidth and matrix-unit
    flops for the platforms the paper targets (v5e is the north-star
    rig, v5p the scale-up check, CPU the dev fallback), overridable per
    run via ``ANALYZER_TPU_PEAK_BYTES_PER_S`` /
    ``ANALYZER_TPU_PEAK_FLOPS_PER_S`` (a rig whose measured STREAM
    number disagrees with the datasheet should pin its own roof).
  * **Cost model** — bytes moved and flops retired per dispatched
    match slot, derived from the known kernel shapes: each slot gathers
    two teams of up to :data:`SLOT_TEAM_SIZE` player rows out of the
    ``[P+1, 16]`` float32 table, runs the closed-form TrueSkill update,
    and scatters the touched rows back (core/state.py documents the row
    layout; sched/superstep.py the ``[W, B, 2, T]`` gather tensors).

graftlint **GL046** makes this module the one sanctioned home of
peak-magnitude numeric literals (>= 1e10): a bandwidth number pasted
into an analysis module would silently fork the roof the verdicts are
judged against. Everything here is stdlib-only and clock-free — the
roofline never measures, it only divides numbers the caller measured.

``bound_by`` verdict semantics (:func:`roofline`): whichever roof the
dispatch sits closer to names the bound; when BOTH achieved fractions
sit under :data:`OVERHEAD_BOUND_FRAC` the dispatch is not near either
roof and the verdict is ``overhead`` — per-dispatch fixed cost (launch
latency, the dev tunnel) dominates, and the tuning answer is batching /
fusion, not bandwidth.
"""

from __future__ import annotations

import os

ENV_PEAK_BYTES = "ANALYZER_TPU_PEAK_BYTES_PER_S"
ENV_PEAK_FLOPS = "ANALYZER_TPU_PEAK_FLOPS_PER_S"

#: Nominal per-chip roofs. Bandwidth is HBM (CPU: a typical desktop
#: DDR figure); flops are the chip's headline dense bf16 number —
#: deliberately the CEILING: the scan kernel is elementwise f32 VPU
#: work, so its achieved fraction reads honestly low.
PEAKS: dict[str, dict] = {
    "v5e": {
        "bytes_per_s": 819.0e9,
        "flops_per_s": 197.0e12,
        "label": "TPU v5e (819 GB/s HBM, 197 bf16 TFLOP/s)",
    },
    "v5p": {
        "bytes_per_s": 2765.0e9,
        "flops_per_s": 459.0e12,
        "label": "TPU v5p (2765 GB/s HBM, 459 bf16 TFLOP/s)",
    },
    "cpu": {
        "bytes_per_s": 50.0e9,
        "flops_per_s": 200.0e9,
        "label": "CPU (nominal 50 GB/s DDR, 200 GFLOP/s)",
    },
}

#: Below this achieved fraction of BOTH roofs, the dispatch is bound by
#: neither memory nor compute: fixed per-dispatch overhead dominates.
OVERHEAD_BOUND_FRAC = 0.05

# -- Kernel-shape constants (the cost model's inputs) -------------------
# Mirrors core/state.py TABLE_WIDTH (16 f32 columns per player row) and
# core/state.py MAX_TEAM_SIZE (two teams of up to 5 players per match
# slot); tests pin the mirror so drift fails loudly.
TABLE_ROW_BYTES = 16 * 4
SLOT_TEAM_SIZE = 5
#: int32 player index + mask per gathered slot position.
SLOT_INDEX_BYTES = 2 * 4
#: Closed-form TrueSkill update per match slot: per-player seed checks,
#: the team mu/sigma reductions, v/w via the Normal pdf/cdf rationals,
#: and the per-player mean/variance writeback — an order-of-magnitude
#: MODEL constant (like sched/superstep.py's cost model), not a
#: measurement.
FLOPS_PER_MATCH_SLOT = 640.0


def classify(platform: str | None = None,
             device_kind: str | None = None) -> str:
    """Peak-table key for a jax device's (platform, device_kind). An
    unrecognized TPU generation maps to v5e (the paper's target rig);
    everything else falls back to the CPU row."""
    kind = (device_kind or "").lower().replace(" ", "")
    if "v5e" in kind or "v5lite" in kind:
        return "v5e"
    if "v5p" in kind:
        return "v5p"
    if (platform or "").lower() == "tpu":
        return "v5e"
    return "cpu"


def peaks_for(platform: str | None = None, device_kind: str | None = None,
              env=os.environ) -> dict:
    """The roof pair for a device, env overrides applied. ``source``
    says whether the numbers came from the table or the operator."""
    key = classify(platform, device_kind)
    base = PEAKS[key]
    out = {
        "platform": key,
        "label": base["label"],
        "bytes_per_s": float(base["bytes_per_s"]),
        "flops_per_s": float(base["flops_per_s"]),
        "source": "table",
    }
    if env.get(ENV_PEAK_BYTES):
        out["bytes_per_s"] = float(env[ENV_PEAK_BYTES])
        out["source"] = "env"
    if env.get(ENV_PEAK_FLOPS):
        out["flops_per_s"] = float(env[ENV_PEAK_FLOPS])
        out["source"] = "env"
    return out


def slot_cost(n_slots: int, team_size: int = SLOT_TEAM_SIZE) -> dict:
    """Bytes/flops for ``n_slots`` dispatched match slots: per slot,
    ``2 * team_size`` player rows gathered (read) and scattered back
    (write) plus the int32 index/mask tensors, and one closed-form
    update's flops."""
    players = n_slots * 2 * team_size
    return {
        "slots": int(n_slots),
        "bytes": int(players * (2 * TABLE_ROW_BYTES + SLOT_INDEX_BYTES)),
        "flops": int(n_slots * FLOPS_PER_MATCH_SLOT),
    }


def dispatch_cost(n_steps: int, batch_size: int,
                  team_size: int = SLOT_TEAM_SIZE) -> dict:
    """Cost of a packed schedule: ``n_steps x batch_size`` slots
    (padding included — pad slots move bytes too)."""
    return slot_cost(int(n_steps) * int(batch_size), team_size=team_size)


def stream_cost(n_matches: int, team_size: int = SLOT_TEAM_SIZE) -> dict:
    """Cost keyed by match count (no schedule in hand — the migrate
    backfill's shape): a lower bound, padding excluded."""
    return slot_cost(int(n_matches), team_size=team_size)


def roofline(bytes_: float, flops: float, device_s: float,
             platform: str | None = None, device_kind: str | None = None,
             device_idle_frac: float | None = None, source: str = "wall",
             env=os.environ) -> dict:
    """The artifact ``roofline`` block: achieved bytes/s and flop/s over
    ``device_s``, fraction of each roof, and the bound-by verdict.
    ``source`` records where the device time came from (``profile`` =
    measured device-busy time from a capture; ``wall`` = the repeat
    minimum, an upper bound on device time)."""
    peak = peaks_for(platform, device_kind, env=env)
    if device_s and device_s > 0:
        abps = float(bytes_) / device_s
        afps = float(flops) / device_s
    else:
        abps = afps = 0.0
    frac_bw = abps / peak["bytes_per_s"] if peak["bytes_per_s"] > 0 else 0.0
    frac_fl = afps / peak["flops_per_s"] if peak["flops_per_s"] > 0 else 0.0
    if max(frac_bw, frac_fl) < OVERHEAD_BOUND_FRAC:
        bound = "overhead"
    elif frac_bw >= frac_fl:
        bound = "memory"
    else:
        bound = "compute"
    out = {
        "device_s": round(float(device_s), 6),
        "device_time_source": source,
        "bytes": int(bytes_),
        "flops": int(flops),
        "achieved_bytes_per_s": round(abps, 1),
        "achieved_flops_per_s": round(afps, 1),
        "frac_of_peak_bw": round(frac_bw, 6),
        "frac_of_peak_flops": round(frac_fl, 6),
        "bound_by": bound,
        "peak": peak,
    }
    if device_idle_frac is not None:
        out["device_idle_frac"] = round(float(device_idle_frac), 4)
    return out


def render_roofline(roof: dict) -> str:
    """One-paragraph human render of a ``roofline`` block."""
    peak = roof.get("peak") or {}
    lines = [
        f"roofline ({peak.get('label', '?')}; peaks from "
        f"{peak.get('source', '?')}, device time from "
        f"{roof.get('device_time_source', '?')}):",
        f"  achieved {roof['achieved_bytes_per_s'] / 1e9:.3f} GB/s "
        f"({100 * roof['frac_of_peak_bw']:.2f}% of peak bw), "
        f"{roof['achieved_flops_per_s'] / 1e9:.3f} GFLOP/s "
        f"({100 * roof['frac_of_peak_flops']:.2f}% of peak flops)",
        f"  bound by: {roof['bound_by']}",
    ]
    if roof.get("device_idle_frac") is not None:
        lines.append(
            f"  device idle inside the capture window: "
            f"{100 * roof['device_idle_frac']:.1f}%"
        )
    return "\n".join(lines) + "\n"
