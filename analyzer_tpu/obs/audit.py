"""Continuous shadow audit: replay a sample of LIVE served queries
through the bit-exact oracle.

The serving plane's numerical contract — every served number equals the
pure-Python float32 oracle bit for bit (``serve/oracle.py``, ISSUE 4) —
was until now a TEST-TIME property: strong at merge, silent in
production, where a bad kernel flag, a driver upgrade or an FMA-happy
compiler build could quietly bend the contract between releases. The
shadow auditor turns it into a MONITORED production invariant:

  * the query engine offers every successfully served response to the
    auditor at resolution time (one hash + one bounded-deque append —
    nothing on the serving path waits for a replay);
  * the auditor keeps a DETERMINISTIC sample: a seeded BLAKE2 hash of
    the query key (kind + payload) selects 1-in-``sample_denom``
    queries, so the sampled set is a pure function of (seed, traffic) —
    identical across runs, topologies and whether anything drains it
    (pinned by test);
  * ``drain()`` — called OFF the hot path (the worker's poll-loop SLO
    tick, the soak driver's tick, explicit in tests) — replays each
    sampled response against the served view's host table through
    :mod:`analyzer_tpu.serve.oracle` and compares BIT FOR BIT;
  * a divergence counts ``audit.mismatches_total`` (the zero-tolerance
    objective ``zero-audit-mismatches`` in :mod:`obs.slo` — the
    watchdog flips /readyz and captures evidence), drops a flight-
    recorder breadcrumb naming the query, and keeps a bounded
    mismatch list for the artifact/operator.

Topology-blind: the auditor touches only the ``ServePlane``-adjacent
view surface every plane provides — ``host_table()`` (a DESIGNATED
merge helper), ``n_players``, ``resolve``, ``id_of``, ``version`` — so
the single-device and sharded planes audit identically.
"""

from __future__ import annotations

import hashlib
import math
import threading
from collections import deque

from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs.registry import get_registry

logger = get_logger(__name__)

#: Default sampling: 1 in N served queries replays through the oracle.
DEFAULT_SAMPLE_DENOM = 8

#: Bounded replay queue — each entry pins its view until drained, so
#: the cap bounds both memory and view retention.
MAX_PENDING = 256

#: Bounded mismatch evidence list (full counts ride the counters).
MAX_MISMATCHES = 64


def query_key(kind: str, payload) -> str:
    """The canonical sampling key for one query. ``repr`` of the
    engine's payload tuples is deterministic (strings/ints/tuples)."""
    return f"{kind}:{payload!r}"


def sampled(key: str, seed: int, denom: int) -> bool:
    """The deterministic sampling decision: a seeded BLAKE2 of the
    query key, 1-in-``denom``. Pure function of (seed, key) — no RNG
    state, no clock, no ordering dependence."""
    if denom <= 1:
        return True
    h = hashlib.blake2s(
        key.encode(), salt=str(seed).encode()[:8]
    ).digest()
    return int.from_bytes(h[:8], "big") % denom == 0


class ShadowAuditor:
    """The audit pipeline: ``offer`` on the serving path (cheap,
    sampled), ``drain`` off it (oracle replay + bit compare)."""

    def __init__(
        self,
        cfg=None,
        tier_edges=None,
        seed: int = 0,
        sample_denom: int = DEFAULT_SAMPLE_DENOM,
        max_pending: int = MAX_PENDING,
    ) -> None:
        from analyzer_tpu.config import RatingConfig

        self.cfg = cfg or RatingConfig()
        self.tier_edges = tier_edges
        self.seed = int(seed)
        self.sample_denom = max(1, int(sample_denom))
        self._lock = threading.Lock()
        self._pending: deque = deque(maxlen=max_pending)
        self.offered = 0
        self.sampled = 0
        self.checked = 0
        self.mismatch_count = 0
        self.dropped = 0
        self.mismatches: list[dict] = []

    # -- serving-path half -------------------------------------------------
    def offer(self, kind: str, payload, response, view) -> bool:
        """Called by the engine at response resolution: one hash, one
        append when sampled. Returns whether the query was sampled.
        Never raises into the serving path."""
        try:
            self.offered += 1
            key = query_key(kind, payload)
            if not sampled(key, self.seed, self.sample_denom):
                return False
            with self._lock:
                if len(self._pending) == self._pending.maxlen:
                    self.dropped += 1
                self._pending.append((kind, payload, response, view))
            self.sampled += 1
            get_registry().counter("audit.sampled_total").add(1)
            get_registry().gauge("audit.backlog").set(len(self._pending))
            return True
        except Exception:  # noqa: BLE001 — the audit must never cost a query
            logger.exception("shadow-audit offer failed")
            return False

    # -- off-hot-path half -------------------------------------------------
    def drain(self, limit: int | None = None) -> int:
        """Replays up to ``limit`` pending samples through the oracle
        (None = everything queued). Returns how many were checked."""
        checked = 0
        while limit is None or checked < limit:
            with self._lock:
                if not self._pending:
                    break
                kind, payload, response, view = self._pending.popleft()
            self._check(kind, payload, response, view)
            checked += 1
        if checked:
            reg = get_registry()
            reg.counter("audit.checked_total").add(checked)
            reg.gauge("audit.backlog").set(len(self._pending))
        return checked

    @property
    def backlog(self) -> int:
        with self._lock:
            return len(self._pending)

    def stats(self) -> dict:
        """The artifact's ``audit`` block / operator summary."""
        return {
            "enabled": True,
            "sample_denom": self.sample_denom,
            "offered": self.offered,
            "sampled": self.sampled,
            "checked": self.checked,
            "mismatches": self.mismatch_count,
            "dropped": self.dropped,
            "backlog": self.backlog,
        }

    # -- the oracle replay -------------------------------------------------
    def _check(self, kind: str, payload, response, view) -> None:
        try:
            expected = self._replay(kind, payload, view)
        except Exception as err:  # noqa: BLE001 — a replay crash is an
            # audit failure, not a serving failure; surface it as a
            # mismatch so it cannot rot silently.
            expected = f"<replay error: {err!r}>"
        self.checked += 1
        if expected == response:
            return
        self.mismatch_count += 1
        get_registry().counter("audit.mismatches_total").add(1)
        record = {
            "kind": kind,
            "key": query_key(kind, payload),
            "version": getattr(view, "version", None),
            "served": response,
            "oracle": expected,
        }
        if len(self.mismatches) < MAX_MISMATCHES:
            self.mismatches.append(record)
        logger.error(
            "SHADOW AUDIT MISMATCH: %s v%s served %r, oracle says %r",
            record["key"], record["version"], response, expected,
        )
        from analyzer_tpu.obs.flight import get_flight_recorder

        get_flight_recorder().note(
            "audit.mismatch", query_kind=kind, key=record["key"],
            version=record["version"],
        )

    def _replay(self, kind: str, payload, view) -> dict:
        """Reconstructs the response the engine SHOULD have served,
        from the view's host table through the pure-Python oracle —
        every float the engine emitted retraced in the same float32
        order (serve/oracle.py's parity contract)."""
        from analyzer_tpu.core.state import (
            COL_SEED_MU,
            COL_SEED_SIGMA,
            MU_LO,
            SIGMA_LO,
        )
        from analyzer_tpu.serve import oracle

        table = view.host_table()
        version = view.version
        if kind == "ratings":
            out = []
            unknown = []
            for pid in payload:
                row = view.resolve(pid)
                if row is None:
                    unknown.append(pid)
                    continue
                mu = float(table[row, MU_LO])
                rated = not math.isnan(mu)
                out.append({
                    "id": pid,
                    "rated": rated,
                    "mu": mu if rated else None,
                    "sigma": float(table[row, SIGMA_LO]) if rated else None,
                    "conservative": (
                        float(oracle.conservative_score(table, row))
                        if rated else None
                    ),
                    "seed_mu": float(table[row, COL_SEED_MU]),
                    "seed_sigma": float(table[row, COL_SEED_SIGMA]),
                })
            return {"version": version, "ratings": out, "unknown": unknown}
        if kind == "winprob":
            team_a, team_b = payload
            rows_a = [view.resolve(p) for p in team_a]
            rows_b = [view.resolve(p) for p in team_b]
            beta2 = self.cfg.beta2
            return {
                "version": version,
                "p_a": float(
                    oracle.win_probability(table, rows_a, rows_b, beta2)
                ),
                "quality": float(
                    oracle.quality(table, rows_a, rows_b, beta2)
                ),
            }
        if kind == "leaderboard":
            k = payload
            leaders = []
            for rank, (row, score) in enumerate(
                oracle.leaderboard(table, view.n_players, k)
            ):
                leaders.append({
                    "rank": rank + 1,
                    "id": view.id_of(row),
                    "mu": float(table[row, MU_LO]),
                    "sigma": float(table[row, SIGMA_LO]),
                    "conservative": float(score),
                })
            return {"version": version, "leaders": leaders}
        if kind == "tiers":
            edges = self.tier_edges
            if edges is None:
                from analyzer_tpu.serve.engine import DEFAULT_TIER_EDGES

                edges = DEFAULT_TIER_EDGES
            counts, rated = oracle.tier_histogram(
                table, view.n_players, edges
            )
            return {
                "version": version,
                "edges": [float(e) for e in edges],
                "counts": counts,
                "rated": rated,
            }
        if kind == "percentile":
            below, rated = oracle.percentile(
                table, view.n_players, payload
            )
            import numpy as np

            return {
                "version": version,
                "score": float(np.float32(payload)),
                "below": below,
                "rated": rated,
                "percentile": (below / rated) if rated else None,
            }
        raise ValueError(f"unknown audited query kind {kind!r}")
