"""Bench trajectory monitoring: diff two bench JSON artifacts.

The repo accumulates one benchmark artifact per round (``BENCH_rNN.json``
for the write path, ``SERVE_BENCH_rNN.json`` for the read path,
``SOAK_rNN.json`` for the closed loop) but
nothing ever LOOKED at the sequences — a 20% regression would ride along
unnoticed until a human happened to eyeball two files. ``cli benchdiff``
turns each trajectory into a gate:

  * loads two artifacts (either the raw one-line JSON ``bench.py`` /
    ``experiments/serve_bench.py`` print, or the driver's wrapper with
    the line under ``"parsed"``);
  * prints a per-config delta table — for the write family the headline
    device throughput + the streamed end-to-end minimum, for the serve
    family coalesced queries/sec (higher is better) + p99 latency
    (lower is better);
  * exits non-zero when any non-degraded config regressed past
    ``--regress-pct``.

Degraded captures (``capture.degraded`` — a bad tunnel window, an
unconverged repeat set) are REPORTED but excluded from the gate: failing
CI on a known-bad measurement teaches people to ignore the gate.

Stdlib-only, like the rest of the exposition layer.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os


@dataclasses.dataclass(frozen=True)
class BenchConfig:
    """One measured configuration inside a bench artifact."""

    name: str
    value: float
    higher_is_better: bool
    degraded: bool


@dataclasses.dataclass(frozen=True)
class DiffRow:
    name: str
    a: float
    b: float
    delta_pct: float
    regressed: bool
    gated: bool  # False when a degraded capture excluded it from the gate


def load_bench(path: str) -> dict:
    """One bench artifact as the raw metric line, unwrapping the driver's
    ``{"parsed": {...}}`` capture format. Raises ValueError when neither
    shape fits — a truncated artifact must not diff as zeros."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if "metric" not in data and isinstance(data.get("parsed"), dict):
        data = data["parsed"]
    if "metric" not in data or "value" not in data:
        raise ValueError(
            f"{path}: not a bench artifact (no metric/value, and no "
            "parsed block)"
        )
    return data


def _roofline_config(data: dict, degraded: bool) -> BenchConfig | None:
    """The roofline ledger's gated config: device-idle fraction inside
    the measured capture window (lower is better — rising idle means
    dispatches shrank relative to launch overhead). Only artifacts whose
    capture actually measured device time carry it; a candidate that
    silently stopped parsing its profile is caught by the ``profile.
    parsed`` vanished-block gate in ``cli benchdiff``, not here."""
    roof = data.get("roofline") or {}
    if roof.get("device_idle_frac") is None:
        return None
    return BenchConfig(
        name="roofline.device_idle_frac",
        value=float(roof["device_idle_frac"]),
        higher_is_better=False,
        degraded=degraded,
    )


def bench_configs(data: dict) -> list[BenchConfig]:
    """The comparable configs inside one artifact.

    Write family (``BENCH_*``): the headline throughput (higher is
    better) and, when present, the capture's ``min_over_predicted``
    ratio against the calibrated cost model (lower is better — a quiet
    capture drifting above the model is the kernel regressing even when
    the tunnel masks absolute time), the streamed end-to-end minimum
    (seconds — lower is better) plus the streamed ``min_over_device``
    ratio (lower is better; the feed-overlap gate), and the fused
    kernel's ``min_over_reference`` (lower is better: <1.0 = the
    VMEM-resident window kernel beats the reference; a regression back
    toward 1.0 — including a silent fallback to the reference kernel —
    fails the gate). Serve family (``SERVE_BENCH_*``, metric
    ``serve.*``): coalesced queries/sec (higher) and the client-observed
    p99 latency in ms (lower) from the ``latency_ms`` block."""
    capture = data.get("capture") or {}
    degraded = bool(capture.get("degraded"))
    out = [
        BenchConfig(
            name=str(data["metric"]),
            value=float(data["value"]),
            higher_is_better=True,
            degraded=degraded,
        )
    ]
    if str(data["metric"]).startswith("soak."):
        # Soak family (``SOAK_*``, metric ``soak.*``): wall ingest
        # matches/s (higher) + the query workload's client-observed p99
        # (lower). The ABSOLUTE SLOs (dead letters, retraces, view
        # staleness, drain) are not deltas — :func:`soak_slo_violations`
        # gates them on the candidate alone.
        latency = data.get("latency_ms") or {}
        if latency.get("p99") is not None:
            out.append(
                BenchConfig(
                    name="soak.p99_ms",
                    value=float(latency["p99"]),
                    higher_is_better=False,
                    degraded=degraded,
                )
            )
        # Rating-quality scores (the artifact's `quality` block,
        # obs/quality.py): Brier and ECE diff lower-is-better, so a
        # candidate that degrades calibration FAILS the soak family
        # even when its throughput improved. A candidate that LOSES
        # the block entirely is gated separately (`cli benchdiff
        # --family soak` fails a vanished quality block outright
        # rather than silently diffing fewer configs).
        quality = data.get("quality") or {}
        if quality.get("brier") is not None:
            out.append(
                BenchConfig(
                    name="quality.brier",
                    value=float(quality["brier"]),
                    higher_is_better=False,
                    degraded=degraded,
                )
            )
        if quality.get("ece") is not None:
            out.append(
                BenchConfig(
                    name="quality.ece",
                    value=float(quality["ece"]),
                    higher_is_better=False,
                    degraded=degraded,
                )
            )
        return out
    if str(data["metric"]).startswith("fabric."):
        # Fabric family (``FABRIC_BENCH_*``, metric
        # ``fabric.matches_per_sec_per_host``): per-host ingest
        # matches/s (higher — the scaling headline), the routed query
        # workload's client-observed p99 (lower — the cross-host read
        # tax), and the worst per-host view staleness in ticks (lower —
        # a host whose version stopped advancing under load is the
        # protocol regressing even when throughput holds). The absolute
        # SLOs gate on the candidate alone (:func:`fabric_slo_
        # violations`); the silent fall-back to a single-process
        # topology is the --family fabric vanished-block gate in ``cli
        # benchdiff``, not a delta here.
        measured = data.get("measured") or {}
        if measured.get("remote_lookup_p99_ms") is not None:
            out.append(
                BenchConfig(
                    name="fabric.remote_lookup_p99_ms",
                    value=float(measured["remote_lookup_p99_ms"]),
                    higher_is_better=False,
                    degraded=degraded,
                )
            )
        det = data.get("deterministic") or {}
        if det.get("view_staleness_ticks_max") is not None:
            out.append(
                BenchConfig(
                    name="fabric.view_staleness_ticks_max",
                    value=float(det["view_staleness_ticks_max"]),
                    higher_is_better=False,
                    degraded=degraded,
                )
            )
        return out
    if str(data["metric"]).startswith("ingest."):
        # Ingest family (``INGEST_BENCH_*``, metric
        # ``ingest.bytes_per_sec``): decoded bytes/s (higher), the
        # queue-to-H2D per-window latency p99 (lower — the time from a
        # window's decode completing to its device slab being ready),
        # and the staging arena's slab hit rate (higher — a collapse
        # means steady-state allocation churn came back). A candidate
        # whose decode silently fell back to the python codec drops
        # ``ingest.native`` — the --family ingest gate in ``cli
        # benchdiff`` fails that outright rather than diffing the
        # (much slower) fallback numbers as a mere regression.
        ingest = data.get("ingest") or {}
        i_degraded = degraded or not ingest.get("stable", True)
        out[0] = dataclasses.replace(out[0], degraded=i_degraded)
        latency = data.get("latency_ms") or {}
        if latency.get("p99") is not None:
            out.append(
                BenchConfig(
                    name="ingest.queue_to_h2d_p99_ms",
                    value=float(latency["p99"]),
                    higher_is_better=False,
                    degraded=i_degraded,
                )
            )
        arena = data.get("arena") or {}
        if arena.get("hit_rate") is not None:
            out.append(
                BenchConfig(
                    name="ingest.arena_hit_rate",
                    value=float(arena["hit_rate"]),
                    higher_is_better=True,
                    degraded=i_degraded,
                )
            )
        roof = _roofline_config(data, i_degraded)
        if roof is not None:
            out.append(roof)
        return out
    if str(data["metric"]).startswith("migrate."):
        # Migrate family (``MIGRATE_BENCH_*``, metric
        # ``migrate.matches_per_sec``): backfill throughput under live
        # serve load (higher), the live plane's client-observed p99
        # DURING the migration (lower — the whole point of the
        # admission-arbitrated backfill is that this number holds), and
        # the cutover pause (lower — readers must never notice the
        # swap). A candidate that silently fell back to the offline
        # (non-streamed) re-rate drops ``migrate.streamed`` — the
        # --family migrate gate in ``cli benchdiff`` fails that outright
        # rather than diffing a different engine's numbers.
        migrate = data.get("migrate") or {}
        m_degraded = degraded or not migrate.get("stable", True)
        out[0] = dataclasses.replace(out[0], degraded=m_degraded)
        latency = data.get("latency_ms") or {}
        if latency.get("p99") is not None:
            out.append(
                BenchConfig(
                    name="migrate.live_p99_ms",
                    value=float(latency["p99"]),
                    higher_is_better=False,
                    degraded=m_degraded,
                )
            )
        if migrate.get("cutover_pause_ms") is not None:
            out.append(
                BenchConfig(
                    name="migrate.cutover_pause_ms",
                    value=float(migrate["cutover_pause_ms"]),
                    higher_is_better=False,
                    degraded=m_degraded,
                )
            )
        assign = data.get("assign") or {}
        if assign.get("matches_per_sec") is not None:
            # Front-half-only assignment throughput (higher): the
            # GIL-released native windowed first-fit vs its python
            # fallback is a ~two-orders gap, so a silent route change
            # dwarfs any honest regression — the assign-native gate in
            # ``cli benchdiff --family migrate`` fails the route flip
            # outright, and this config catches the in-route slowdowns.
            out.append(
                BenchConfig(
                    name="assign.matches_per_sec",
                    value=float(assign["matches_per_sec"]),
                    higher_is_better=True,
                    degraded=m_degraded,
                )
            )
        roof = _roofline_config(data, m_degraded)
        if roof is not None:
            out.append(roof)
        return out
    if str(data["metric"]).startswith("serve."):
        latency = data.get("latency_ms") or {}
        if latency.get("p99") is not None:
            out.append(
                BenchConfig(
                    name="serve.p99_ms",
                    value=float(latency["p99"]),
                    higher_is_better=False,
                    degraded=degraded,
                )
            )
        sharded = data.get("sharded") or {}
        if sharded.get("min_over_single") is not None:
            # The shard-plane tax (sharded batched seconds / single
            # batched seconds, lower is better): a routed-lookup
            # regression or a merge gone quadratic moves this ratio even
            # when the headline single-engine qps holds. A candidate
            # with NO sharded block at all (silent fall-back to the
            # single-device plane) is caught by the serve family's
            # vanished-block check in ``cli benchdiff``.
            s_degraded = degraded or not sharded.get("stable", True)
            out.append(
                BenchConfig(
                    name="sharded.min_over_single",
                    value=float(sharded["min_over_single"]),
                    higher_is_better=False,
                    degraded=s_degraded,
                )
            )
            if sharded.get("queries_per_sec") is not None:
                out.append(
                    BenchConfig(
                        name="sharded.queries_per_sec",
                        value=float(sharded["queries_per_sec"]),
                        higher_is_better=True,
                        degraded=s_degraded,
                    )
                )
        frontdoor = data.get("frontdoor") or {}
        if frontdoor.get("queries_per_sec") is not None:
            # The socket plane (serve/frontdoor.py): end-to-end qps over
            # pipelined keep-alive connections (higher) and the client-
            # observed p99 while a publisher thread republishes the view
            # (lower — the number an operator pages on). A candidate that
            # silently lost the native codec (``native`` false) is failed
            # outright by the serve family's vanished-native gate in
            # ``cli benchdiff`` instead of being diffed as an honest
            # regression.
            f_degraded = degraded or not frontdoor.get("stable", True)
            out.append(
                BenchConfig(
                    name="frontdoor.queries_per_sec",
                    value=float(frontdoor["queries_per_sec"]),
                    higher_is_better=True,
                    degraded=f_degraded,
                )
            )
            if frontdoor.get("p99_ms_under_publish") is not None:
                out.append(
                    BenchConfig(
                        name="frontdoor.p99_ms_under_publish",
                        value=float(frontdoor["p99_ms_under_publish"]),
                        higher_is_better=False,
                        degraded=f_degraded,
                    )
                )
        return out
    if capture.get("min_over_predicted") is not None:
        out.append(
            BenchConfig(
                name="capture.min_over_predicted",
                value=float(capture["min_over_predicted"]),
                higher_is_better=False,
                degraded=degraded,
            )
        )
    fused = data.get("fused") or {}
    if fused.get("min_over_reference") is not None:
        out.append(
            BenchConfig(
                name="fused.min_over_reference",
                value=float(fused["min_over_reference"]),
                higher_is_better=False,
                degraded=degraded or not fused.get("stable", True),
            )
        )
    tiered = data.get("tiered") or {}
    if tiered.get("min_over_resident") is not None:
        # The tiering tax (tiered end-to-end min / resident min, lower is
        # better): thrash — a hot set suddenly too small for the working
        # set, or a promotion path gone synchronous — moves this ratio
        # even when absolute time is masked by the tunnel. A SILENT
        # fall-back to the untiered path drops the block entirely, which
        # the --family tiered gate reports as a vanished config.
        t_degraded = degraded or not tiered.get("stable", True)
        out.append(
            BenchConfig(
                name="tiered.min_over_resident",
                value=float(tiered["min_over_resident"]),
                higher_is_better=False,
                degraded=t_degraded,
            )
        )
        if tiered.get("hit_rate") is not None:
            # Hot-set hit rate (higher is better): the leading indicator
            # of thrash — it collapses before the wall clock does.
            out.append(
                BenchConfig(
                    name="tiered.hit_rate",
                    value=float(tiered["hit_rate"]),
                    higher_is_better=True,
                    degraded=t_degraded,
                )
            )
    streamed = data.get("streamed") or {}
    if streamed.get("min_s") is not None:
        out.append(
            BenchConfig(
                name="streamed.min_s",
                value=float(streamed["min_s"]),
                higher_is_better=False,
                degraded=degraded or not streamed.get("stable", True),
            )
        )
    if streamed.get("min_over_device") is not None:
        # The streamed-feed overlap ratio (end-to-end min / device-only
        # min, lower is better; 1.0 = the feed fully hides behind the
        # device scan). Gated alongside the absolute seconds: a future
        # change that re-serializes the feed moves this ratio even when
        # a faster kernel or a quieter tunnel masks the absolute time.
        out.append(
            BenchConfig(
                name="streamed.min_over_device",
                value=float(streamed["min_over_device"]),
                higher_is_better=False,
                degraded=degraded or not streamed.get("stable", True),
            )
        )
    roof = _roofline_config(data, degraded)
    if roof is not None:
        out.append(roof)
    return out


def diff_configs(
    a: list[BenchConfig], b: list[BenchConfig], regress_pct: float
) -> list[DiffRow]:
    """Per-config deltas for configs present on BOTH sides (a new config
    has no baseline; a dropped one has no candidate — neither can gate)."""
    a_by = {c.name: c for c in a}
    rows: list[DiffRow] = []
    for cb in b:
        ca = a_by.get(cb.name)
        if ca is None or ca.value == 0:
            continue
        delta_pct = (cb.value - ca.value) / abs(ca.value) * 100.0
        worse = -delta_pct if ca.higher_is_better else delta_pct
        regressed = worse > regress_pct
        gated = not (ca.degraded or cb.degraded)
        rows.append(
            DiffRow(
                name=cb.name,
                a=ca.value,
                b=cb.value,
                delta_pct=delta_pct,
                regressed=regressed,
                gated=gated,
            )
        )
    return rows


#: Artifact family name -> filename prefix (``cli benchdiff --family``).
#: ``tiered`` scans the same BENCH artifacts but gates only the tiered
#: configs (``tiered.min_over_resident`` + the hit-rate delta) — see
#: :func:`family_configs`. Prefix-disambiguation contract: each family's
#: glob anchors on its full prefix, so ``BENCH_*`` must never swallow
#: ``SERVE_BENCH_*`` or ``SOAK_*`` files (pinned by the family tests).
FAMILIES = {
    "bench": "BENCH",
    "serve": "SERVE_BENCH",
    "tiered": "BENCH",
    "soak": "SOAK",
    "ingest": "INGEST_BENCH",
    "migrate": "MIGRATE_BENCH",
    "fabric": "FABRIC_BENCH",
}


def family_configs(
    configs: list[BenchConfig], family: str
) -> list[BenchConfig]:
    """Restricts a config list to the family's own gate. The ``tiered``
    family compares only ``tiered.*`` configs: a tier-thrash regression
    must fail on its own ratio even when headline throughput holds, and
    a capture that silently fell back to untiered (no tiered block at
    all) shows up as "no comparable configs" instead of a clean pass.
    The ``soak`` family likewise keeps only ``soak.*`` plus the
    rating-quality ``quality.*`` configs (its absolute SLO gate is
    :func:`soak_slo_violations`, not a delta)."""
    if family == "tiered":
        return [c for c in configs if c.name.startswith("tiered.")]
    if family == "soak":
        return [c for c in configs if c.name.startswith(("soak.", "quality."))]
    if family == "fabric":
        return [c for c in configs if c.name.startswith("fabric.")]
    if family == "ingest":
        return [c for c in configs if c.name.startswith("ingest.")]
    if family == "migrate":
        # assign.* rides the migrate family: the front-half-only
        # throughput is captured by the same MIGRATE_BENCH artifact.
        return [
            c for c in configs
            if c.name.startswith(("migrate.", "assign."))
        ]
    return configs


def soak_slo_violations(data: dict) -> list[str]:
    """The soak family's ABSOLUTE gate: zero dead letters, flat
    steady-state retraces, bounded view staleness, a drained backlog,
    every published match rated, zero shadow-audit mismatches — plus
    the optional absolute throughput/latency floors the soak was
    configured with (``slo.thresholds``). Returns human-readable
    violation strings; empty means the artifact passes.

    Since the live SLO plane landed this is a thin delegate to the ONE
    declarative objective table (``obs/slo.py STANDARD_OBJECTIVES``):
    ``SoakDriver``'s verdict, this CI gate, and the live watchdog all
    walk the same objective set — doctor one objective and all three
    consumers trip (pinned by tests/test_slo_plane.py)."""
    from analyzer_tpu.obs.slo import soak_violations

    return soak_violations(data)


def fabric_slo_violations(data: dict) -> list[str]:
    """The fabric family's ABSOLUTE gate, re-derived from the
    candidate's artifact alone (the CI mirror of
    ``FabricSoakDriver._violations``): every published match rated,
    zero dead letters fleet-wide, per-host view staleness within the
    configured tick bound, zero steady-state retraces on every host
    (when the capture warmed up), and no fleet objective burning.
    Returns human-readable violation strings; empty means pass."""
    det = data.get("deterministic") or {}
    fleet = data.get("fleet") or {}
    thresholds = (data.get("slo") or {}).get("thresholds") or {}
    cfg = data.get("config") or {}
    out = []
    published = det.get("matches_published")
    rated = det.get("matches_rated")
    if published is not None and rated is not None and rated < published:
        out.append(f"lost work: {published} published, {rated} rated")
    if det.get("dead_letters"):
        out.append(f"dead letters: {det['dead_letters']}")
    lag_max = thresholds.get("max_view_lag_ticks")
    staleness = det.get("view_staleness_ticks_max")
    if lag_max is not None and staleness is not None and staleness > lag_max:
        out.append(
            f"view staleness {staleness} ticks exceeds {lag_max}"
        )
    if cfg.get("warmup"):
        for h in fleet.get("hosts") or []:
            if h.get("retraces_steady", 0) > 0:
                out.append(
                    f"host {h.get('host')}: "
                    f"{h['retraces_steady']:.0f} steady-state retraces"
                )
    for name in fleet.get("burning") or []:
        out.append(f"fleet objective burning: {name}")
    return out


#: Causal tracing must stay (nearly) free when enabled: the bench's
#: ``trace_overhead`` block measures the same end-to-end line with the
#: trace context bound vs off, and the gate fails a candidate whose
#: tracing tax exceeds this.
TRACE_OVERHEAD_MAX_PCT = 2.0


def trace_overhead_violations(data: dict) -> list[str]:
    """The bench family's absolute tracing-tax gate, derived from the
    candidate alone: a ``trace_overhead`` block whose ``overhead_pct``
    exceeds :data:`TRACE_OVERHEAD_MAX_PCT` is a violation. Degraded
    captures and unconverged overhead pairs are excluded (same contract
    as the delta gate: a known-bad measurement must not train people to
    ignore CI). No block at all passes — tracing overhead is only
    gateable where it was measured."""
    block = data.get("trace_overhead")
    if not isinstance(block, dict):
        return []
    if (data.get("capture") or {}).get("degraded"):
        return []
    if not block.get("stable", True):
        return []
    pct = block.get("overhead_pct")
    if pct is None or float(pct) <= TRACE_OVERHEAD_MAX_PCT:
        return []
    return [
        f"trace_overhead: tracing-on run is {float(pct):+.2f}% vs "
        f"tracing-off (gate: <= {TRACE_OVERHEAD_MAX_PCT:g}%)"
    ]


#: The live SLO plane must stay (nearly) free when armed: the bench's
#: ``watchdog_overhead`` block measures the same end-to-end line with
#: the history sampler + watchdog + shadow-audit drain riding the chunk
#: boundaries vs off, and the gate fails a candidate whose plane tax
#: exceeds this — same contract as the tracing gate above.
WATCHDOG_OVERHEAD_MAX_PCT = 2.0


def watchdog_overhead_violations(data: dict) -> list[str]:
    """The bench family's absolute SLO-plane-tax gate, derived from the
    candidate alone: a ``watchdog_overhead`` block whose
    ``overhead_pct`` exceeds :data:`WATCHDOG_OVERHEAD_MAX_PCT` is a
    violation. Degraded captures and unconverged pairs are excluded; no
    block at all passes — the tax is only gateable where measured."""
    block = data.get("watchdog_overhead")
    if not isinstance(block, dict):
        return []
    if (data.get("capture") or {}).get("degraded"):
        return []
    if not block.get("stable", True):
        return []
    pct = block.get("overhead_pct")
    if pct is None or float(pct) <= WATCHDOG_OVERHEAD_MAX_PCT:
        return []
    return [
        f"watchdog_overhead: SLO-plane-on run is {float(pct):+.2f}% vs "
        f"off (gate: <= {WATCHDOG_OVERHEAD_MAX_PCT:g}%)"
    ]


#: Federation must stay (nearly) free for the scraped worker: the
#: bench's ``federate_overhead`` block measures the same end-to-end
#: line with a fleet Collector scraping obsd under load vs unscraped,
#: and the gate fails a candidate whose scrape tax exceeds this — the
#: same contract as the tracing and SLO-plane gates above.
FEDERATE_OVERHEAD_MAX_PCT = 2.0


def federate_overhead_violations(data: dict) -> list[str]:
    """The bench family's absolute federation-tax gate, derived from
    the candidate alone: a ``federate_overhead`` block whose
    ``overhead_pct`` exceeds :data:`FEDERATE_OVERHEAD_MAX_PCT` is a
    violation. Degraded captures and unconverged pairs are excluded; no
    block at all passes — the tax is only gateable where measured."""
    block = data.get("federate_overhead")
    if not isinstance(block, dict):
        return []
    if (data.get("capture") or {}).get("degraded"):
        return []
    if not block.get("stable", True):
        return []
    pct = block.get("overhead_pct")
    if pct is None or float(pct) <= FEDERATE_OVERHEAD_MAX_PCT:
        return []
    return [
        f"federate_overhead: scraped-under-load run is {float(pct):+.2f}% "
        f"vs unscraped (gate: <= {FEDERATE_OVERHEAD_MAX_PCT:g}%)"
    ]


def find_bench_artifacts(directory: str, family: str = "bench") -> list[str]:
    """``<PREFIX>_*.json`` under ``directory``, name-sorted (the round
    numbering ``r01..rNN`` sorts chronologically by construction). The
    write family's glob must not swallow the serve family's files —
    ``BENCH_*`` would match ``SERVE_BENCH_*`` as a substring only with
    a sloppier pattern, so both globs anchor on the full prefix."""
    prefix = FAMILIES[family]
    return [
        p
        for p in sorted(glob.glob(os.path.join(directory, prefix + "_*.json")))
        if os.path.basename(p).startswith(prefix + "_")
    ]


def latest_artifact(
    directory: str, exclude: str | None = None, family: str = "bench"
) -> str | None:
    """The newest artifact by name order, skipping ``exclude`` (the
    candidate itself, when it already sits in the scanned directory)."""
    paths = find_bench_artifacts(directory, family=family)
    if exclude is not None:
        ex = os.path.abspath(exclude)
        paths = [p for p in paths if os.path.abspath(p) != ex]
    return paths[-1] if paths else None


def render_diff(
    a_path: str, b_path: str, rows: list[DiffRow]
) -> str:
    """The human table. One line per config: old -> new, signed percent,
    and the gate disposition."""
    out = [f"benchdiff: {os.path.basename(a_path)} -> "
           f"{os.path.basename(b_path)}"]
    if not rows:
        out.append("  (no comparable configs)")
    for r in rows:
        status = "ok"
        if r.regressed:
            status = "REGRESSION" if r.gated else "regression (degraded capture, not gated)"
        elif not r.gated:
            status = "degraded capture, not gated"
        out.append(
            f"  {r.name}: {r.a:g} -> {r.b:g} "
            f"({r.delta_pct:+.1f}%) {status}"
        )
    return "\n".join(out) + "\n"
