"""Span tracer: bounded in-memory ring, Chrome trace-event JSONL export.

Spans are complete events (``ph: "X"``) in the Chrome trace-event format,
so the export opens directly in Perfetto / ``chrome://tracing`` — next to
the XLA traces ``utils.trace`` captures, which use the same timeline UI.
Timestamps are microseconds on a per-tracer monotonic epoch
(``perf_counter``-based), with the wall-clock epoch recorded once in the
tracer so a snapshot consumer can reconstruct absolute times.

The ring is bounded (default 20k events) and lock-guarded: the pipeline
writer thread and the consumer thread both emit spans. Emission cost is
two ``perf_counter`` calls, one dict, one deque append — cheap enough for
per-batch and per-chunk granularity, NOT for per-match use.

Export is JSONL: one complete JSON trace event per line. Perfetto's JSON
importer accepts this (the trace-event "JSON array format" is tolerant of
a missing enclosing array), and line-oriented output means a crashed run
still leaves a loadable prefix.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque

# Causal-trace binding (obs/tracectx.py): the CURRENT trace id for this
# thread, attached to every event emitted while bound. Lives here (not
# in tracectx) so _append needs no import and an unbound thread pays one
# thread-local getattr per event — nothing allocates when tracing is off.
_tls = threading.local()


def current_trace() -> str | None:
    """The trace id bound to this thread (None when unbound)."""
    return getattr(_tls, "trace", None)


@contextlib.contextmanager
def bind_trace(trace: str | None):
    """Binds ``trace`` as this thread's causal context: every span and
    instant emitted inside the block gains ``args["trace"] = trace``.
    ``None`` is a no-op, so call sites need no enabled-check of their
    own. Re-entrant — the previous binding is restored on exit."""
    if trace is None:
        yield
        return
    prev = getattr(_tls, "trace", None)
    _tls.trace = trace
    try:
        yield
    finally:
        _tls.trace = prev


class Tracer:
    def __init__(self, maxlen: int = 20_000) -> None:
        self._lock = threading.Lock()
        self._events: deque[dict] = deque(maxlen=maxlen)
        self.epoch_wall = time.time()
        self.epoch_perf = time.perf_counter()
        self.dropped = 0

    def _now_us(self) -> float:
        return (time.perf_counter() - self.epoch_perf) * 1e6

    def _append(self, event: dict) -> None:
        trace = getattr(_tls, "trace", None)
        if trace is not None:
            # The causal id rides in args so existing span consumers
            # (Perfetto, snapshots) need no format change; setdefault
            # keeps an explicit trace=/batch= arg authoritative.
            event["args"].setdefault("trace", trace)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(event)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "app", **args):
        """Times a block as one complete trace event. ``args`` must be
        JSON-serializable scalars (they land in the event's ``args``)."""
        t0 = self._now_us()
        try:
            yield
        finally:
            t1 = self._now_us()
            self._append({
                "name": name,
                "cat": cat,
                "ph": "X",
                "ts": round(t0, 1),
                "dur": round(t1 - t0, 1),
                "pid": os.getpid(),
                "tid": threading.get_ident() % 1_000_000,
                "args": args,
            })

    def instant(self, name: str, cat: str = "app", **args) -> None:
        """A zero-duration marker (``ph: "i"``) — dead-letters, engine
        degradations, retraces."""
        self._append({
            "name": name,
            "cat": cat,
            "ph": "i",
            "s": "t",  # thread-scoped instant
            "ts": round(self._now_us(), 1),
            "pid": os.getpid(),
            "tid": threading.get_ident() % 1_000_000,
            "args": args,
        })

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    def export_chrome(self, path: str) -> int:
        """Writes the ring as Chrome trace-event JSONL; returns the event
        count (the leading metadata line excluded). The first line is a
        ``trace_epoch`` metadata event carrying this tracer's wall-clock
        epoch — what lets the trace stitcher (obs/traceview.py
        ``load_forest``) align exports from DIFFERENT processes onto one
        timeline; Perfetto ignores unknown metadata."""
        events = self.events()
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps({
                "name": "trace_epoch", "cat": "__metadata", "ph": "M",
                "ts": 0.0, "pid": os.getpid(), "tid": 0,
                "args": {"epoch_wall": self.epoch_wall},
            }) + "\n")
            for event in events:
                f.write(json.dumps(event) + "\n")
        return len(events)


_tracer_lock = threading.Lock()
_tracer: Tracer | None = None


def get_tracer() -> Tracer:
    """The process-wide tracer (created on first use)."""
    global _tracer
    with _tracer_lock:
        if _tracer is None:
            _tracer = Tracer()
        return _tracer


def reset_tracer() -> Tracer:
    """Replaces the process-wide tracer with a fresh one (tests)."""
    global _tracer
    with _tracer_lock:
        _tracer = Tracer()
        return _tracer


def span(name: str, cat: str = "app", **args):
    """Module-level convenience: a span on the process-wide tracer."""
    return get_tracer().span(name, cat=cat, **args)


def instant(name: str, cat: str = "app", **args) -> None:
    """Module-level convenience: an instant on the process-wide tracer."""
    get_tracer().instant(name, cat=cat, **args)
