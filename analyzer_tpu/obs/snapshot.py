"""Snapshot exposition: one JSON artifact, Prometheus text, summaries.

The snapshot is the ``--metrics-out`` contract: everything the process
measured — counter/gauge values, histogram quantile summaries, retrace
counts per tracked jitted entrypoint, and the tracer's span ring — in one
JSON object a bench artifact can embed and ``cli metrics`` can re-render.

Prometheus text exposition follows the text format conventions (names
sanitized to ``[a-zA-Z0-9_:]``, histograms as summaries with quantile
labels) so a node exporter textfile collector or a debug scrape can lift
the same numbers without the JSON shape.
"""

from __future__ import annotations

import json
import re
import time

from analyzer_tpu.obs.registry import MetricsRegistry, get_registry
from analyzer_tpu.obs.retrace import retrace_counts
from analyzer_tpu.obs.tracer import Tracer, get_tracer

SNAPSHOT_VERSION = 1

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
# DOTALL: a label value carrying a newline (an exception string) must
# still parse as a label body, then escape as \n in the exposition.
_SERIES_RE = re.compile(r"^(?P<name>[^{]+)(\{(?P<labels>.*)\})?$", re.DOTALL)


def snapshot(
    registry: MetricsRegistry | None = None,
    tracer: Tracer | None = None,
    max_spans: int | None = None,
) -> dict:
    """The full JSON-ready telemetry snapshot of this process."""
    registry = registry or get_registry()
    tracer = tracer or get_tracer()
    spans = tracer.events()
    if max_spans is not None and len(spans) > max_spans:
        spans = spans[-max_spans:]
    return {
        "version": SNAPSHOT_VERSION,
        "ts": time.time(),
        "trace_epoch_wall": tracer.epoch_wall,
        **registry.snapshot(),
        "retraces": retrace_counts(),
        "spans": spans,
        "spans_dropped": tracer.dropped,
    }


def write_snapshot(path: str, **kwargs) -> dict:
    """Writes :func:`snapshot` as JSON; returns the snapshot."""
    snap = snapshot(**kwargs)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(snap, f, indent=1, sort_keys=True)
        f.write("\n")
    return snap


def write_chrome_trace(path: str, tracer: Tracer | None = None) -> int:
    """Exports the span ring as Chrome trace-event JSONL (Perfetto-
    loadable); returns the event count."""
    return (tracer or get_tracer()).export_chrome(path)


def escape_label_value(value: str) -> str:
    """Prometheus text-format label escaping: backslash, double quote and
    newline must be escaped or the scrape line is corrupt (a player id or
    an exception string with a quote in it would break the whole page)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _split_series(key: str) -> tuple[str, str]:
    """``name{a=b,c=d}`` -> (sanitized_name, prometheus label body)."""
    m = _SERIES_RE.match(key)
    name = _NAME_RE.sub("_", (m.group("name") if m else key))
    labels = (m.group("labels") if m else None) or ""
    if labels:
        parts = []
        for pair in labels.split(","):
            k, _, v = pair.partition("=")
            parts.append(f'{_NAME_RE.sub("_", k)}="{escape_label_value(v)}"')
        labels = ",".join(parts)
    return name, labels


def _coerce(value) -> float | None:
    if value is None:
        return None
    if isinstance(value, bool):
        return 1.0 if value else 0.0
    return float(value)


def prometheus_text(snap: dict | None = None) -> str:
    """Prometheus text-format exposition of a snapshot (or of the live
    process when ``snap`` is None). Retrace counts surface as
    ``jax_jit_cache_size{entrypoint="..."}``."""
    snap = snap if snap is not None else snapshot(max_spans=0)
    lines: list[str] = []
    typed: set[str] = set()

    def emit(key: str, value, mtype: str, extra_labels: str = "") -> None:
        v = _coerce(value)
        if v is None:
            return
        name, labels = _split_series(key)
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {mtype}")
        body = ",".join(x for x in (labels, extra_labels) if x)
        series = f"{name}{{{body}}}" if body else name
        lines.append(f"{series} {v:g}")

    for key, value in snap.get("counters", {}).items():
        emit(key, value, "counter")
    for key, value in snap.get("gauges", {}).items():
        emit(key, value, "gauge")
    for key, summ in snap.get("histograms", {}).items():
        name, labels = _split_series(key)
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} summary")
        prefix = f"{{{labels}," if labels else "{"
        for q in ("p50", "p90", "p99"):
            if summ.get(q) is not None:
                lines.append(
                    f'{name}{prefix}quantile="0.{q[1:]}"}} {summ[q]:g}'
                )
        body = f"{{{labels}}}" if labels else ""
        lines.append(f"{name}_sum{body} {summ['sum']:g}")
        lines.append(f"{name}_count{body} {summ['count']:g}")
    for entry, count in snap.get("retraces", {}).items():
        emit(
            "jax.jit_cache_size", count, "gauge",
            extra_labels=f'entrypoint="{escape_label_value(entry)}"',
        )
    return "\n".join(lines) + "\n"


def render_summary(snap: dict) -> str:
    """A short human-facing digest of a snapshot (``cli metrics``):
    non-zero counters, set gauges, histogram p50/p99, retraces, span
    count."""
    out: list[str] = []
    counters = {
        k: v for k, v in snap.get("counters", {}).items() if v
    }
    if counters:
        out.append("counters:")
        out.extend(f"  {k} = {v:g}" for k, v in counters.items())
    gauges = {
        k: v for k, v in snap.get("gauges", {}).items() if v not in (None, 0)
    }
    if gauges:
        out.append("gauges:")
        out.extend(f"  {k} = {v}" for k, v in gauges.items())
    hists = {
        k: s for k, s in snap.get("histograms", {}).items() if s.get("count")
    }
    if hists:
        out.append("histograms:")
        for k, s in hists.items():
            out.append(
                f"  {k}: n={s['count']} mean={s['mean']:.6g}"
                f" p50={s['p50']:.6g} p99={s['p99']:.6g} max={s['max']:.6g}"
            )
    retraces = snap.get("retraces", {})
    if retraces:
        out.append("jit cache sizes (compiled variants per entrypoint):")
        out.extend(f"  {k} = {v}" for k, v in sorted(retraces.items()))
    spans = snap.get("spans", [])
    out.append(
        f"spans: {len(spans)} buffered"
        + (f" ({snap['spans_dropped']} dropped)" if snap.get("spans_dropped")
           else "")
    )
    return "\n".join(out) + "\n"
