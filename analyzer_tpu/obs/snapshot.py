"""Snapshot exposition: one JSON artifact, Prometheus text, summaries.

The snapshot is the ``--metrics-out`` contract: everything the process
measured — counter/gauge values, histogram quantile summaries, retrace
counts per tracked jitted entrypoint, and the tracer's span ring — in one
JSON object a bench artifact can embed and ``cli metrics`` can re-render.

Prometheus text exposition follows the text format conventions (names
sanitized to ``[a-zA-Z0-9_:]``, histograms as summaries with quantile
labels) so a node exporter textfile collector or a debug scrape can lift
the same numbers without the JSON shape.
"""

from __future__ import annotations

import json
import re
import time

from analyzer_tpu.obs.registry import MetricsRegistry, get_registry
from analyzer_tpu.obs.retrace import retrace_counts
from analyzer_tpu.obs.tracer import Tracer, get_tracer

SNAPSHOT_VERSION = 1

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
# DOTALL: a label value carrying a newline (an exception string) must
# still parse as a label body, then escape as \n in the exposition.
_SERIES_RE = re.compile(r"^(?P<name>[^{]+)(\{(?P<labels>.*)\})?$", re.DOTALL)


def snapshot(
    registry: MetricsRegistry | None = None,
    tracer: Tracer | None = None,
    max_spans: int | None = None,
) -> dict:
    """The full JSON-ready telemetry snapshot of this process."""
    registry = registry or get_registry()
    tracer = tracer or get_tracer()
    spans = tracer.events()
    if max_spans is not None and len(spans) > max_spans:
        spans = spans[-max_spans:]
    return {
        "version": SNAPSHOT_VERSION,
        "ts": time.time(),
        "trace_epoch_wall": tracer.epoch_wall,
        **registry.snapshot(),
        "retraces": retrace_counts(),
        "spans": spans,
        "spans_dropped": tracer.dropped,
    }


def write_snapshot(path: str, **kwargs) -> dict:
    """Writes :func:`snapshot` as JSON; returns the snapshot."""
    snap = snapshot(**kwargs)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(snap, f, indent=1, sort_keys=True)
        f.write("\n")
    return snap


def write_chrome_trace(path: str, tracer: Tracer | None = None) -> int:
    """Exports the span ring as Chrome trace-event JSONL (Perfetto-
    loadable); returns the event count."""
    return (tracer or get_tracer()).export_chrome(path)


def escape_label_value(value: str) -> str:
    """Prometheus text-format label escaping: backslash, double quote and
    newline must be escaped or the scrape line is corrupt (a player id or
    an exception string with a quote in it would break the whole page)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _split_series(key: str) -> tuple[str, str]:
    """``name{a=b,c=d}`` -> (sanitized_name, prometheus label body)."""
    m = _SERIES_RE.match(key)
    name = _NAME_RE.sub("_", (m.group("name") if m else key))
    labels = (m.group("labels") if m else None) or ""
    if labels:
        parts = []
        for pair in labels.split(","):
            k, _, v = pair.partition("=")
            parts.append(f'{_NAME_RE.sub("_", k)}="{escape_label_value(v)}"')
        labels = ",".join(parts)
    return name, labels


def _coerce(value) -> float | None:
    if value is None:
        return None
    if isinstance(value, bool):
        return 1.0 if value else 0.0
    return float(value)


def prometheus_text(snap: dict | None = None) -> str:
    """Prometheus text-format exposition of a snapshot (or of the live
    process when ``snap`` is None). Every family leads with its
    ``# HELP`` / ``# TYPE`` pair — HELP text from the STANDARD schema
    catalog (``obs.registry.SCHEMA_HELP``), TYPE from the bucket the
    series lives in (counters as ``counter``, gauges as ``gauge``,
    histograms as ``summary``). Retrace counts surface as
    ``jax_jit_cache_size{entrypoint="..."}``. :func:`parse_prometheus_text`
    round-trips this output."""
    from analyzer_tpu.obs.registry import schema_help

    snap = snap if snap is not None else snapshot(max_spans=0)
    lines: list[str] = []
    typed: set[str] = set()

    def declare(name: str, family: str, mtype: str) -> None:
        if name in typed:
            return
        typed.add(name)
        text = schema_help(family).replace("\\", "\\\\").replace("\n", "\\n")
        lines.append(f"# HELP {name} {text}")
        lines.append(f"# TYPE {name} {mtype}")

    def emit(key: str, value, mtype: str, extra_labels: str = "") -> None:
        v = _coerce(value)
        if v is None:
            return
        name, labels = _split_series(key)
        declare(name, key.split("{", 1)[0], mtype)
        body = ",".join(x for x in (labels, extra_labels) if x)
        series = f"{name}{{{body}}}" if body else name
        lines.append(f"{series} {v:g}")

    for key, value in snap.get("counters", {}).items():
        emit(key, value, "counter")
    for key, value in snap.get("gauges", {}).items():
        emit(key, value, "gauge")
    for key, summ in snap.get("histograms", {}).items():
        name, labels = _split_series(key)
        declare(name, key.split("{", 1)[0], "summary")
        prefix = f"{{{labels}," if labels else "{"
        for q in ("p50", "p90", "p99"):
            if summ.get(q) is not None:
                lines.append(
                    f'{name}{prefix}quantile="0.{q[1:]}"}} {summ[q]:g}'
                )
        body = f"{{{labels}}}" if labels else ""
        lines.append(f"{name}_sum{body} {summ['sum']:g}")
        lines.append(f"{name}_count{body} {summ['count']:g}")
    for entry, count in snap.get("retraces", {}).items():
        emit(
            "jax.jit_cache_size", count, "gauge",
            extra_labels=f'entrypoint="{escape_label_value(entry)}"',
        )
    return "\n".join(lines) + "\n"


_LABEL_RE = re.compile(r'([a-zA-Z0-9_]+)="((?:\\.|[^"\\])*)"')
_PROM_LINE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(\{(?P<labels>.*)\})?\s+(?P<value>\S+)$"
)
_QUANTILE_OF = {"0.5": "p50", "0.50": "p50", "0.9": "p90", "0.90": "p90",
                "0.99": "p99"}


def _unescape_label_value(value: str) -> str:
    return (
        value.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
    )


def _unsanitize_map() -> dict[str, str]:
    """sanitized exposition name -> the registry's dotted family name,
    built from the STANDARD schema catalog (the exposition's name
    sanitization is lossy — ``worker.acks_total`` and a hypothetical
    ``worker_acks_total`` collide — so the catalog is the only way
    back)."""
    from analyzer_tpu.obs.registry import (
        SCHEMA_HELP,
        STANDARD_COUNTERS,
        STANDARD_GAUGES,
        STANDARD_HISTOGRAMS,
    )

    out: dict[str, str] = {}
    for name in (
        *STANDARD_COUNTERS, *STANDARD_GAUGES, *STANDARD_HISTOGRAMS,
        *SCHEMA_HELP,
    ):
        out[_NAME_RE.sub("_", name)] = name
    return out


def parse_prometheus_text(text: str) -> dict:
    """Parses a :func:`prometheus_text` exposition back into the
    snapshot shape: ``counters``/``gauges`` as ``{series_key: value}``,
    ``histograms`` as ``{series_key: {p50/p90/p99/sum/count}}``, plus
    the scraped ``help`` and ``types`` per family. Series keys are the
    registry's ``name{label=value,...}`` format with dotted names
    recovered through the STANDARD schema catalog — the exposition/
    parse pair round-trips every cataloged series (pinned by
    tests/test_obs.py). Unknown families keep their sanitized names and
    parse by their ``# TYPE`` line; lines with neither are skipped."""
    unsanitize = _unsanitize_map()
    out = {
        "counters": {}, "gauges": {}, "histograms": {},
        "help": {}, "types": {},
    }

    def family(name: str) -> str:
        return unsanitize.get(name, name)

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            kind, rest = line[2:6], line[7:]
            name, _, body = rest.partition(" ")
            if kind == "HELP":
                out["help"][family(name)] = (
                    body.replace("\\n", "\n").replace("\\\\", "\\")
                )
            else:
                out["types"][family(name)] = body.strip()
            continue
        if line.startswith("#"):
            continue
        m = _PROM_LINE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name = m.group("name")
        value = float(m.group("value"))
        labels = {
            k: _unescape_label_value(v)
            for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        quantile = labels.pop("quantile", None)
        hist_field = None
        base = name
        if quantile is not None:
            hist_field = _QUANTILE_OF.get(quantile)
        elif name.endswith("_sum") and out["types"].get(
            family(name[:-4])
        ) == "summary":
            base, hist_field = name[:-4], "sum"
        elif name.endswith("_count") and out["types"].get(
            family(name[:-6])
        ) == "summary":
            base, hist_field = name[:-6], "count"
        fam = family(base)
        inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
        key = f"{fam}{{{inner}}}" if inner else fam
        if hist_field is not None:
            entry = out["histograms"].setdefault(key, {})
            entry[hist_field] = int(value) if hist_field == "count" else value
            continue
        mtype = out["types"].get(fam, "gauge")
        bucket = "counters" if mtype == "counter" else "gauges"
        out[bucket][key] = value
    return out


def render_summary(snap: dict) -> str:
    """A short human-facing digest of a snapshot (``cli metrics``):
    non-zero counters, set gauges, histogram p50/p99, retraces, span
    count."""
    out: list[str] = []
    counters = {
        k: v for k, v in snap.get("counters", {}).items() if v
    }
    if counters:
        out.append("counters:")
        out.extend(f"  {k} = {v:g}" for k, v in counters.items())
    gauges = {
        k: v for k, v in snap.get("gauges", {}).items() if v not in (None, 0)
    }
    if gauges:
        out.append("gauges:")
        out.extend(f"  {k} = {v}" for k, v in gauges.items())
    hists = {
        k: s for k, s in snap.get("histograms", {}).items() if s.get("count")
    }
    if hists:
        out.append("histograms:")
        for k, s in hists.items():
            out.append(
                f"  {k}: n={s['count']} mean={s['mean']:.6g}"
                f" p50={s['p50']:.6g} p99={s['p99']:.6g} max={s['max']:.6g}"
            )
    retraces = snap.get("retraces", {})
    if retraces:
        out.append("jit cache sizes (compiled variants per entrypoint):")
        out.extend(f"  {k} = {v}" for k, v in sorted(retraces.items()))
    spans = snap.get("spans", [])
    out.append(
        f"spans: {len(spans)} buffered"
        + (f" ({snap['spans_dropped']} dropped)" if snap.get("spans_dropped")
           else "")
    )
    return "\n".join(out) + "\n"
