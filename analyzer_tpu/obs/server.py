"""obsd: the live introspection plane — stdlib HTTP endpoints on a thread.

Everything the snapshot artifact exposes post-hoc (``--metrics-out``,
``cli metrics``) becomes scrapeable while the process runs:

  ``GET /healthz``         liveness — 200 as long as the thread serves;
  ``GET /readyz``          readiness — 200 when every registered
                           :class:`HealthChecks` probe passes, 503 with
                           one ``fail <name>: <detail>`` line per failing
                           probe otherwise (a worker registers pipeline/
                           broker/store probes — and a ``serve.view``
                           probe when the query-serving plane is on,
                           ``service/worker.py``);
  ``GET /metrics``         Prometheus text exposition (``prometheus_text``);
  ``GET /statusz``         human summary: ``render_summary`` plus the
                           owner's ``status_provider()`` dict (worker
                           ``stats()``), the served view's version AND
                           age, and trend sparklines from the history
                           rings;
  ``GET /historyz``        the telemetry history rings as JSON
                           (``obs/history.py`` — ``?series=<prefix>``
                           filters by name prefix, ``?tier=raw|10s|1m``
                           picks one downsampling tier);
  ``GET /sloz``            the SLO watchdog's objective table and
                           burn states (``obs/slo.py``);
  ``GET /qualityz``        the rating-quality ledger's reliability
                           table, streaming brier/log-loss/ECE and
                           population-drift snapshot
                           (``obs/quality.py``);
  ``GET /debug/snapshot``  the full JSON snapshot, spans included;
  ``GET /debug/flight``    TRIGGERS a flight-recorder dump
                           (``?reason=...``) — the fleet Collector's
                           evidence-capture hook (obs/federate.py):
                           localhost-only regardless of the bind, and
                           token-authenticated when a token is
                           configured (``flight_token=`` /
                           ``ANALYZER_TPU_FLIGHT_TOKEN``); throttling
                           stays the recorder's (per reason).

Served through the shared :mod:`analyzer_tpu.obs.httpd` plumbing (route
table + daemon ``ThreadingHTTPServer``) — no framework, no dependency,
good enough for a scrape every few seconds and an operator's curl. The
listening-socket machinery lives in ``obs/httpd.py``; graftlint GL024
flags ``http.server`` imports outside ``analyzer_tpu/obs/`` +
``analyzer_tpu/serve/``, and flags a bare ``0.0.0.0`` default bind
anywhere — every plane binds localhost unless an operator explicitly
widens it (``docs/observability.md``).
"""

from __future__ import annotations

import json
import threading

from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs.httpd import DEFAULT_HOST, RoutedHTTPServer, text_body
from analyzer_tpu.obs.snapshot import (
    prometheus_text,
    render_summary,
    snapshot,
)

logger = get_logger(__name__)

__all__ = [
    "DEFAULT_HOST", "HealthChecks", "ObsServer", "connectivity_probe",
]


class HealthChecks:
    """Pluggable readiness registry: ``register(name, probe)`` where
    ``probe()`` returns ``True``/``False`` or ``(ok, detail)``. A probe
    that raises is a failing probe (the exception is the detail) — a
    readiness endpoint that crashes on the condition it exists to report
    would be worse than useless."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._checks: dict[str, object] = {}

    def register(self, name: str, probe) -> None:
        with self._lock:
            self._checks[name] = probe

    def unregister(self, name: str) -> None:
        with self._lock:
            self._checks.pop(name, None)

    def run(self) -> dict[str, tuple[bool, str]]:
        """name -> (ok, detail) for every registered probe."""
        with self._lock:
            checks = dict(self._checks)
        out: dict[str, tuple[bool, str]] = {}
        for name, probe in checks.items():
            try:
                result = probe()
            except Exception as err:  # noqa: BLE001 — a raising probe is a failing probe
                out[name] = (False, f"probe raised: {err!r}")
                continue
            if isinstance(result, tuple):
                ok, detail = result
                out[name] = (bool(ok), str(detail))
            else:
                out[name] = (bool(result), "ok" if result else "failed")
        return out

    @property
    def ready(self) -> bool:
        return all(ok for ok, _ in self.run().values())


class ObsServer:
    """The obsd thread. ``port=0`` binds an ephemeral port (tests); the
    bound port is readable at :attr:`port`. ``status_provider()`` (a dict,
    e.g. ``Worker.stats``) enriches ``/statusz``. Stop with
    :meth:`close` — the worker's shutdown path owns that call."""

    def __init__(
        self,
        port: int = 0,
        host: str = DEFAULT_HOST,
        status_provider=None,
        health: HealthChecks | None = None,
        max_statusz_spans: int = 200,
        flight_dump=None,
        flight_token: str | None = None,
    ) -> None:
        import os

        self.health = health if health is not None else HealthChecks()
        self.status_provider = status_provider
        self._max_statusz_spans = max_statusz_spans
        # /debug/flight: the dump hook (the worker passes its own so a
        # remote-triggered artifact carries config + profiler info like
        # a local one) and the shared-secret token. No token configured
        # = localhost peers may trigger untokened (the endpoint is
        # loopback-gated either way).
        self._flight_dump = flight_dump
        self.flight_token = (
            flight_token
            or os.environ.get("ANALYZER_TPU_FLIGHT_TOKEN")
            or None
        )
        self._httpd = RoutedHTTPServer(
            routes={
                "/healthz": lambda params: text_body("ok\n"),
                "/readyz": self._route_readyz,
                "/metrics": lambda params: text_body(prometheus_text()),
                "/statusz": lambda params: text_body(self._statusz()),
                "/historyz": self._route_historyz,
                "/sloz": self._route_sloz,
                "/qualityz": self._route_qualityz,
                "/debug/snapshot": self._route_snapshot,
                "/debug/flight": self._route_flight,
            },
            port=port,
            host=host,
            name="analyzer-obsd",
            local_only={"/debug/flight"},
        )
        self.host = host
        logger.info("obsd listening on http://%s:%d", self.host, self.port)

    @property
    def port(self) -> int:
        return self._httpd.port

    @property
    def url(self) -> str:
        return self._httpd.url

    def _route_readyz(self, params) -> tuple[int, str, str]:
        code, body = self._readyz()
        return text_body(body, code)

    def _route_snapshot(self, params) -> tuple[int, str, str]:
        body = json.dumps(snapshot(max_spans=None), indent=1, sort_keys=True)
        return 200, body + "\n", "application/json"

    def _route_historyz(self, params) -> tuple[int, str, str]:
        from analyzer_tpu.obs.history import TIERS, get_history

        prefix = params.get("series")
        tier = params.get("tier")
        if tier is not None and tier not in {t for t, _, _ in TIERS}:
            return text_body(
                f"unknown tier {tier!r} (raw|10s|1m)\n", 400
            )
        body = json.dumps(
            get_history().to_json(prefix=prefix, tier=tier),
            indent=1, sort_keys=True,
        )
        return 200, body + "\n", "application/json"

    def _route_sloz(self, params) -> tuple[int, str, str]:
        from analyzer_tpu.obs.slo import get_watchdog

        body = json.dumps(
            get_watchdog().status(), indent=1, sort_keys=True
        )
        return 200, body + "\n", "application/json"

    def _route_qualityz(self, params) -> tuple[int, str, str]:
        """The rating-quality plane (obs/quality.py): the live ledger's
        full reliability table + drift snapshot, or an explicit
        ``enabled: false`` when this process runs no ledger — a scraper
        can tell "plane off" from "broken" (the same presence contract
        as stats()['quality'])."""
        from analyzer_tpu.obs.quality import get_quality_ledger

        ledger = get_quality_ledger()
        payload = (
            {"enabled": False} if ledger is None
            else dict(ledger.summary(), enabled=True)
        )
        body = json.dumps(payload, indent=1, sort_keys=True)
        return 200, body + "\n", "application/json"

    def _route_flight(self, params) -> tuple[int, str, str]:
        """The authenticated-localhost dump trigger: a fleet Collector
        (or an operator's curl on the box) asks THIS process to freeze
        its flight-recorder evidence — used at fleet-burn onset so the
        burning host captures its own trajectory while it burns. The
        recorder's per-reason throttle still applies (a storm of
        requests produces one artifact); the reason is sanitized into
        the artifact directory name by the recorder itself."""
        if self.flight_token is not None and (
            params.get("token") != self.flight_token
        ):
            return (
                403,
                json.dumps({"error": "bad or missing token"}) + "\n",
                "application/json",
            )
        reason = params.get("reason") or "remote"
        if self._flight_dump is not None:
            path = self._flight_dump(reason)
        else:
            from analyzer_tpu.obs.flight import get_flight_recorder

            path = get_flight_recorder().dump(reason)
        body = json.dumps(
            {"reason": reason, "dumped": path}, sort_keys=True
        )
        return 200, body + "\n", "application/json"

    def _readyz(self) -> tuple[int, str]:
        results = self.health.run()
        failing = {n: d for n, (ok, d) in results.items() if not ok}
        lines = [
            (f"fail {n}: {results[n][1]}" if n in failing else f"ok {n}")
            for n in sorted(results)
        ]
        if not lines:
            lines = ["ok (no checks registered)"]
        return (503 if failing else 200), "\n".join(lines) + "\n"

    #: Series whose trends /statusz renders when the history sampler
    #: has data for them (the page-one signals; everything else is one
    #: /historyz query away).
    STATUSZ_TRENDS = (
        "worker.matches_rated_total",
        "worker.dead_letters_total",
        "broker.queue_depth",
        "serve.view_age_seconds",
        "feed.starved_total",
        "tier.host_bytes",
        "device.live_buffers",
        "audit.mismatches_total",
        "quality.matches_scored_total",
    )

    def _statusz(self) -> str:
        snap = snapshot(max_spans=self._max_statusz_spans)
        out = [render_summary(snap)]
        out.extend(self._statusz_history())
        if self.status_provider is not None:
            try:
                status = self.status_provider()
            except Exception as err:  # noqa: BLE001 — statusz must render
                # during the incident it exists to explain
                status = {"status_provider_error": repr(err)}
            out.append("status:")
            out.extend(f"  {k} = {v}" for k, v in sorted(status.items()))
        ready = self.health.run()
        if ready:
            out.append("readiness:")
            out.extend(
                f"  {'ok ' if ok else 'FAIL'} {n}: {d}"
                for n, (ok, d) in sorted(ready.items())
            )
        return "\n".join(out) + "\n"

    def _statusz_history(self) -> list[str]:
        """The history-derived /statusz sections: the served view's
        version WITH its age (staleness is the #1 page — the operator
        must never compute it by hand from two scrapes), and trend
        sparklines for the page-one series. Empty before the first
        sample; never raises into the status page."""
        from analyzer_tpu.obs.history import get_history
        from analyzer_tpu.obs.slo import get_watchdog

        try:
            history = get_history()
            out: list[str] = []
            vv = history.last_change("serve.view_version")
            if vv is not None and vv[1]:
                t_change, version = vv
                age = history.latest("serve.view_age_seconds")
                last_t = history.last_sample_t
                # Age from the ring: prefer the sampled age gauge (set
                # from the publisher's own clock), fall back to "how
                # long has the version sat unchanged" in sampler time.
                if age is not None:
                    age_s = age[1]
                elif last_t is not None:
                    age_s = last_t - t_change
                else:
                    age_s = 0.0
                out.append(
                    f"serve view: v{int(version)} age={age_s:.1f}s"
                )
            burning = get_watchdog().burning
            if burning:
                out.append("SLO BURNING: " + ", ".join(burning))
            trends = []
            for name in self.STATUSZ_TRENDS:
                line = history.sparkline(name)
                if line is None:
                    continue
                latest = history.latest(name)
                trends.append(
                    f"  {name:<36} {line}  last={latest[1]:g}"
                )
            if trends:
                out.append("trends (oldest -> newest; /historyz for data):")
                out.extend(trends)
            return out
        except Exception:  # noqa: BLE001 — statusz must render during
            # the incident it exists to explain
            logger.exception("statusz history section failed")
            return []

    def close(self) -> None:
        """Stops serving and joins the thread. Idempotent."""
        self._httpd.close()
        logger.info("obsd stopped")


def connectivity_probe(obj, what: str):
    """A HealthChecks probe over a duck-typed broker/store: consults
    ``is_connected``/``is_open`` (attr or nullary method) or ``ping()``
    when the object offers one; objects exposing none of these (the
    in-memory fakes) are healthy by construction."""

    def probe() -> tuple[bool, str]:
        for attr in ("is_connected", "is_open"):
            flag = getattr(obj, attr, None)
            if flag is None:
                continue
            ok = bool(flag() if callable(flag) else flag)
            return ok, f"{what}.{attr}={ok}"
        ping = getattr(obj, "ping", None)
        if callable(ping):
            ping()  # raises on a dead connection -> failing probe
            return True, f"{what}.ping ok"
        return True, f"{what}: no connectivity probe exposed"

    return probe
