"""obsd: the live introspection plane — stdlib HTTP endpoints on a thread.

Everything the snapshot artifact exposes post-hoc (``--metrics-out``,
``cli metrics``) becomes scrapeable while the process runs:

  ``GET /healthz``         liveness — 200 as long as the thread serves;
  ``GET /readyz``          readiness — 200 when every registered
                           :class:`HealthChecks` probe passes, 503 with
                           one ``fail <name>: <detail>`` line per failing
                           probe otherwise (a worker registers pipeline/
                           broker/store probes, ``service/worker.py``);
  ``GET /metrics``         Prometheus text exposition (``prometheus_text``);
  ``GET /statusz``         human summary: ``render_summary`` plus the
                           owner's ``status_provider()`` dict (worker
                           ``stats()``);
  ``GET /debug/snapshot``  the full JSON snapshot, spans included.

Served by ``http.server.ThreadingHTTPServer`` on a daemon thread — no
framework, no dependency, good enough for a scrape every few seconds and
an operator's curl. This module is the ONE sanctioned home for a listening
socket in the package: graftlint GL024 flags ``http.server`` imports
anywhere else, and flags a bare ``0.0.0.0`` default bind even here — obsd
binds localhost unless an operator explicitly widens it (``docs/
observability.md``).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from analyzer_tpu.logging_utils import get_logger
from analyzer_tpu.obs.snapshot import (
    prometheus_text,
    render_summary,
    snapshot,
)

logger = get_logger(__name__)

#: Loopback by default: the introspection plane carries operational detail
#: (queue names, env capture pointers) and must be opted ONTO a network
#: interface, never discovered on one.
DEFAULT_HOST = "127.0.0.1"


class HealthChecks:
    """Pluggable readiness registry: ``register(name, probe)`` where
    ``probe()`` returns ``True``/``False`` or ``(ok, detail)``. A probe
    that raises is a failing probe (the exception is the detail) — a
    readiness endpoint that crashes on the condition it exists to report
    would be worse than useless."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._checks: dict[str, object] = {}

    def register(self, name: str, probe) -> None:
        with self._lock:
            self._checks[name] = probe

    def unregister(self, name: str) -> None:
        with self._lock:
            self._checks.pop(name, None)

    def run(self) -> dict[str, tuple[bool, str]]:
        """name -> (ok, detail) for every registered probe."""
        with self._lock:
            checks = dict(self._checks)
        out: dict[str, tuple[bool, str]] = {}
        for name, probe in checks.items():
            try:
                result = probe()
            except Exception as err:  # noqa: BLE001 — a raising probe is a failing probe
                out[name] = (False, f"probe raised: {err!r}")
                continue
            if isinstance(result, tuple):
                ok, detail = result
                out[name] = (bool(ok), str(detail))
            else:
                out[name] = (bool(result), "ok" if result else "failed")
        return out

    @property
    def ready(self) -> bool:
        return all(ok for ok, _ in self.run().values())


class ObsServer:
    """The obsd thread. ``port=0`` binds an ephemeral port (tests); the
    bound port is readable at :attr:`port`. ``status_provider()`` (a dict,
    e.g. ``Worker.stats``) enriches ``/statusz``. Stop with
    :meth:`close` — the worker's shutdown path owns that call."""

    def __init__(
        self,
        port: int = 0,
        host: str = DEFAULT_HOST,
        status_provider=None,
        health: HealthChecks | None = None,
        max_statusz_spans: int = 200,
    ) -> None:
        self.health = health if health is not None else HealthChecks()
        self.status_provider = status_provider
        self._max_statusz_spans = max_statusz_spans
        obsd = self

        class Handler(BaseHTTPRequestHandler):
            # One obsd per process is the norm; route table lives here so
            # the handler closes over the server object, not globals.
            def log_message(self, fmt, *args):  # quiet: curl spam is DEBUG
                logger.debug("obsd: " + fmt, *args)

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype + "; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 — http.server contract
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        self._send(200, "ok\n", "text/plain")
                    elif path == "/readyz":
                        self._send(*obsd._readyz(), "text/plain")
                    elif path == "/metrics":
                        self._send(200, prometheus_text(), "text/plain")
                    elif path == "/statusz":
                        self._send(200, obsd._statusz(), "text/plain")
                    elif path == "/debug/snapshot":
                        body = json.dumps(
                            snapshot(max_spans=None), indent=1, sort_keys=True
                        )
                        self._send(200, body + "\n", "application/json")
                    else:
                        self._send(404, "not found\n", "text/plain")
                except Exception:  # noqa: BLE001 — a broken renderer must
                    # surface as a 500 response, not kill the serving thread.
                    logger.exception("obsd handler failed for %s", path)
                    self._send(500, "internal error\n", "text/plain")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="analyzer-obsd",
            daemon=True,
        )
        self._thread.start()
        logger.info("obsd listening on http://%s:%d", self.host, self.port)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _readyz(self) -> tuple[int, str]:
        results = self.health.run()
        failing = {n: d for n, (ok, d) in results.items() if not ok}
        lines = [
            (f"fail {n}: {results[n][1]}" if n in failing else f"ok {n}")
            for n in sorted(results)
        ]
        if not lines:
            lines = ["ok (no checks registered)"]
        return (503 if failing else 200), "\n".join(lines) + "\n"

    def _statusz(self) -> str:
        snap = snapshot(max_spans=self._max_statusz_spans)
        out = [render_summary(snap)]
        if self.status_provider is not None:
            try:
                status = self.status_provider()
            except Exception as err:  # noqa: BLE001 — statusz must render
                # during the incident it exists to explain
                status = {"status_provider_error": repr(err)}
            out.append("status:")
            out.extend(f"  {k} = {v}" for k, v in sorted(status.items()))
        ready = self.health.run()
        if ready:
            out.append("readiness:")
            out.extend(
                f"  {'ok ' if ok else 'FAIL'} {n}: {d}"
                for n, (ok, d) in sorted(ready.items())
            )
        return "\n".join(out) + "\n"

    def close(self) -> None:
        """Stops serving and joins the thread. Idempotent."""
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        self._thread.join(timeout=5)
        logger.info("obsd stopped")


def connectivity_probe(obj, what: str):
    """A HealthChecks probe over a duck-typed broker/store: consults
    ``is_connected``/``is_open`` (attr or nullary method) or ``ping()``
    when the object offers one; objects exposing none of these (the
    in-memory fakes) are healthy by construction."""

    def probe() -> tuple[bool, str]:
        for attr in ("is_connected", "is_open"):
            flag = getattr(obj, attr, None)
            if flag is None:
                continue
            ok = bool(flag() if callable(flag) else flag)
            return ok, f"{what}.{attr}={ok}"
        ping = getattr(obj, "ping", None)
        if callable(ping):
            ping()  # raises on a dead connection -> failing probe
            return True, f"{what}.ping ok"
        return True, f"{what}: no connectivity probe exposed"

    return probe
