"""Shared stdlib HTTP plumbing for the package's listening planes.

Two subsystems serve HTTP: obsd (``obs/server.py`` — the introspection
plane) and ratesrv (``serve/server.py`` — the query-serving plane). Both
used to need the same dozen lines of ``BaseHTTPRequestHandler`` ritual:
route dispatch, query-string parsing, content-type + length headers, the
500-on-renderer-crash guard, the daemon serving thread, the idempotent
close. This module is that ritual, written once:

  * :class:`RoutedHTTPServer` — a ``ThreadingHTTPServer`` on a daemon
    thread whose GET handler dispatches on the *path* to a route table of
    ``fn(params) -> (status, body, content_type)`` callables (``params``
    is the parsed query string, last-value-wins);
  * :class:`HttpError` — raise from a route to return a clean non-200
    (bad query params, unknown player ids) instead of a 500;
  * :func:`json_body` / :func:`text_body` — response tuple helpers.

Bind policy lives here too: ``DEFAULT_HOST`` is loopback, and widening to
a real interface is an operator's explicit runtime choice — never a code
default (graftlint GL024 enforces both halves: listening-socket imports
stay inside ``analyzer_tpu/obs/`` + ``analyzer_tpu/serve/``, and a bare
``0.0.0.0`` literal is banned everywhere).
"""

from __future__ import annotations

import http.client
import io
import json
import threading
import urllib.error
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from analyzer_tpu.logging_utils import get_logger

logger = get_logger(__name__)

#: Loopback by default: both planes carry operational detail and must be
#: opted ONTO a network interface, never discovered on one.
DEFAULT_HOST = "127.0.0.1"


class HttpError(Exception):
    """A route's clean failure: rendered as ``status`` with a one-line
    plain-text (or JSON, for ``/v1/`` routes) body instead of the 500 the
    crash guard would produce."""

    def __init__(self, status: int, message: str) -> None:
        super().__init__(message)
        self.status = status
        self.message = message


def json_body(obj, status: int = 200) -> tuple[int, str, str]:
    """A JSON response tuple (sorted keys — curl diffs must be stable)."""
    return status, json.dumps(obj, sort_keys=True) + "\n", "application/json"


def text_body(body: str, status: int = 200) -> tuple[int, str, str]:
    return status, body, "text/plain"


class RoutedHTTPServer:
    """A route-table HTTP server on a daemon thread.

    ``routes`` maps an exact path (``"/healthz"``) to
    ``fn(params: dict[str, str]) -> (status, body, content_type)``.
    ``post_routes`` maps a path to ``fn(body) -> (status, body,
    content_type)`` where ``body`` is the request's parsed JSON (None
    for an empty body) — the fabric's control surface
    (``fabric/host.py``) is the first POST plane. ``port=0`` binds an
    ephemeral port (tests); the bound port is readable at :attr:`port`.
    Stop with :meth:`close` (idempotent) — whoever started the plane
    owns that call.
    """

    def __init__(
        self,
        routes: dict,
        port: int = 0,
        host: str = DEFAULT_HOST,
        name: str = "analyzer-httpd",
        json_errors: bool = False,
        local_only: set | None = None,
        post_routes: dict | None = None,
    ) -> None:
        self._routes = dict(routes)
        self._post_routes = dict(post_routes or {})
        self._json_errors = json_errors
        # Paths that ACT (trigger a dump) rather than read: they answer
        # only to loopback peers even when an operator widened the bind
        # to a real interface — a scraper on the network may look, not
        # touch (obsd's /debug/flight; docs/observability.md).
        self._local_only = set(local_only or ())
        server = self

        class Handler(BaseHTTPRequestHandler):
            # The handler closes over the server object, not globals —
            # two planes in one process must not share route tables.

            # Keep-alive: the stdlib default (HTTP/1.0) closes the TCP
            # connection after every response, so each obsd scrape and
            # HttpHostClient lookup paid a fresh handshake. Every _send
            # stamps Content-Length, which is all HTTP/1.1 persistence
            # requires.
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet: curl spam is DEBUG
                logger.debug("%s: " + fmt, name, *args)

            def _send(self, code: int, body: str, ctype: str) -> None:
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype + "; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 — http.server contract
                parsed = urllib.parse.urlsplit(self.path)
                path = parsed.path
                fn = server._routes.get(path)
                if fn is None:
                    self._send(*server._error(404, "not found"))
                    return
                if path in server._local_only and (
                    self.client_address[0] not in ("127.0.0.1", "::1")
                ):
                    self._send(*server._error(
                        403, "localhost-only endpoint"
                    ))
                    return
                params = {
                    k: v[-1]
                    for k, v in urllib.parse.parse_qs(parsed.query).items()
                }
                try:
                    self._send(*fn(params))
                except HttpError as err:
                    self._send(*server._error(err.status, err.message))
                except Exception:  # noqa: BLE001 — a broken route must
                    # surface as a 500 response, not kill the serving
                    # thread the other routes still need.
                    logger.exception("%s route failed for %s", name, path)
                    self._send(*server._error(500, "internal error"))

            def do_POST(self):  # noqa: N802 — http.server contract
                parsed = urllib.parse.urlsplit(self.path)
                path = parsed.path
                fn = server._post_routes.get(path)
                if fn is None:
                    self._send(*server._error(404, "not found"))
                    return
                if path in server._local_only and (
                    self.client_address[0] not in ("127.0.0.1", "::1")
                ):
                    self._send(*server._error(
                        403, "localhost-only endpoint"
                    ))
                    return
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length) if length else b""
                    body = json.loads(raw) if raw else None
                except (ValueError, UnicodeDecodeError):
                    self._send(*server._error(400, "body must be JSON"))
                    return
                try:
                    self._send(*fn(body))
                except HttpError as err:
                    self._send(*server._error(err.status, err.message))
                except Exception:  # noqa: BLE001 — same crash guard as GET
                    logger.exception("%s POST route failed for %s", name, path)
                    self._send(*server._error(500, "internal error"))

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name=name, daemon=True
        )
        self._thread.start()

    def _error(self, status: int, message: str) -> tuple[int, str, str]:
        if self._json_errors:
            return json_body({"error": message}, status)
        return text_body(message + "\n", status)

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        """Stops serving and joins the thread. Idempotent."""
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        self._thread.join(timeout=5)


class PooledHTTPClient:
    """One persistent keep-alive connection to a single ``host:port``.

    The client side of :attr:`Handler.protocol_version` = HTTP/1.1: the
    fabric's ``HttpHostClient`` and the loadgen's ``HttpServeClient``
    used to ``urlopen`` per call — a fresh TCP handshake per lookup, by
    far the dominant cost of a small GET. This pool holds ONE
    ``http.client.HTTPConnection`` and reuses it across requests
    (``frontdoor.pool_reuse_total`` counts the saved handshakes;
    :attr:`reuse_count` is the per-pool view the tests assert on).

    urlopen-compatible failure surface, so the routing/mark-down logic
    above stays untouched: a non-2xx status raises
    :class:`urllib.error.HTTPError` (body readable), a transport
    failure raises :class:`urllib.error.URLError` (an ``OSError``). A
    request that dies on a PREVIOUSLY-USED connection is retried once
    on a fresh one — the server idle-closing between requests is the
    one legal keep-alive race; a fresh-connection failure is real and
    propagates. Thread-safe: one in-flight request at a time (lock);
    callers that want parallelism hold one pool per thread or accept
    the serialization.
    """

    def __init__(self, base_url: str, timeout_s: float = 30.0) -> None:
        parsed = urllib.parse.urlsplit(base_url)
        if parsed.scheme not in ("http", ""):
            raise ValueError(f"PooledHTTPClient is http-only: {base_url!r}")
        self.base_url = base_url.rstrip("/")
        self.host = parsed.hostname or DEFAULT_HOST
        self.port = parsed.port or 80
        self.timeout_s = float(timeout_s)
        self.reuse_count = 0
        self.requests = 0
        self._conn: http.client.HTTPConnection | None = None
        self._lock = threading.Lock()

    def _drop(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def _exchange(self, path_qs: str, fresh: bool) -> bytes:
        conn = self._conn
        conn.request("GET", path_qs)
        resp = conn.getresponse()
        body = resp.read()  # drain fully or the conn can't be reused
        if resp.will_close:
            self._drop()
        if not fresh:
            self.reuse_count += 1
            _registry().counter("frontdoor.pool_reuse_total").add(1)
        if not 200 <= resp.status < 300:
            raise urllib.error.HTTPError(
                self.base_url + path_qs, resp.status, resp.reason,
                resp.headers, io.BytesIO(body),
            )
        return body

    def get(self, path_qs: str) -> bytes:
        """GET ``path_qs`` (path + encoded query) over the pooled
        connection; returns the response body bytes."""
        with self._lock:
            self.requests += 1
            fresh = self._conn is None
            if fresh:
                self._conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout_s
                )
            try:
                return self._exchange(path_qs, fresh)
            except urllib.error.HTTPError:
                raise
            except (http.client.HTTPException, OSError) as err:
                self._drop()
                if fresh:
                    raise urllib.error.URLError(err) from err
                # Stale pooled connection: retry exactly once, fresh.
                self._conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout_s
                )
                try:
                    return self._exchange(path_qs, True)
                except urllib.error.HTTPError:
                    raise
                except (http.client.HTTPException, OSError) as err2:
                    self._drop()
                    raise urllib.error.URLError(err2) from err2

    def close(self) -> None:
        with self._lock:
            self._drop()


def _registry():
    # Lazy: httpd must stay importable in the jax-free CLI paths even
    # if registry wiring changes; the counter is best-effort telemetry.
    from analyzer_tpu.obs.registry import get_registry

    return get_registry()
