"""Duck-typed fake ORM object factories.

The reference's tests hand-build plain classes mirroring only the
attributes ``rate_match`` touches (``worker_test.py:6-63``) — no DB, no
broker, no mocks. These factories keep that strategy (SURVEY.md section 4
calls it the single most important design fact to preserve) but cover the
full 7-column rating schema, including the 5v5 pairs the reference's
fixtures omit.

They live in the package (not under ``tests/``) because production code
uses them too: the worker's warmup cost probe encodes a synthetic
batch-size object graph to measure per-batch host time
(``service/worker.py``). One definition keeps the probe and the parity
tests from drifting when the encoded attribute set changes.
"""

from __future__ import annotations

from types import SimpleNamespace

from analyzer_tpu.core.constants import RATING_COLUMNS


def fake_player(skill_tier=None, rank_points_ranked=None, rank_points_blitz=None,
                **ratings):
    attrs = {"api_id": "", "skill_tier": skill_tier,
             "rank_points_ranked": rank_points_ranked,
             "rank_points_blitz": rank_points_blitz}
    for col in RATING_COLUMNS:
        attrs[f"{col}_mu"] = None
        attrs[f"{col}_sigma"] = None
    attrs.update(ratings)
    return SimpleNamespace(**attrs)


def fake_items(**ratings):
    attrs = {"api_id": "", "any_afk": False}
    for col in RATING_COLUMNS[1:]:
        attrs[f"{col}_mu"] = None
        attrs[f"{col}_sigma"] = None
    attrs.update(ratings)
    return SimpleNamespace(**attrs)


def fake_participant(player=None, items=None, skill_tier=0, went_afk=False):
    return SimpleNamespace(
        api_id="",
        skill_tier=skill_tier,
        went_afk=went_afk,
        trueskill_mu=None,
        trueskill_sigma=None,
        trueskill_delta=None,
        participant_items=[items if items is not None else fake_items()],
        player=[player if player is not None else fake_player()],
    )


def fake_roster(winner, participants):
    return SimpleNamespace(api_id="", winner=winner, participants=participants)


def fake_match(game_mode, rosters, api_id=""):
    return SimpleNamespace(
        api_id=api_id,
        game_mode=game_mode,
        rosters=rosters,
        participants=[p for r in rosters for p in r.participants],
        trueskill_quality=None,
        created_at=0,
    )


def synthetic_raw_batch(n: int, team_size: int = 3,
                        game_mode: str = "ranked") -> dict:
    """``n`` well-formed two-team matches as a ``load_batch_raw``-shaped
    raw row bundle (the columnar lane's input) — the warmup cost probe's
    counterpart of :func:`synthetic_batch` for stores that will run the
    columnar lane in production. Fresh tier-15 players, full 7-pair
    rating schema, one items row per participant."""
    pl_rating = [f"{c}_{x}" for c in RATING_COLUMNS for x in ("mu", "sigma")]
    it_rating = [
        f"{c}_{x}" for c in RATING_COLUMNS[1:] for x in ("mu", "sigma")
    ]
    match_rows, roster_rows, part_rows = [], [], []
    player_rows, items_rows = [], []
    for m in range(n):
        mid = f"warm_m{m}"
        match_rows.append((mid, game_mode, m))
        for t in range(2):
            rid = f"{mid}-r{t}"
            roster_rows.append((rid, mid, int(t == 0)))
            for s in range(team_size):
                pid = f"warm_{m}_{t}_{s}"
                paid = f"{mid}-{t}-{s}"
                part_rows.append((paid, mid, rid, pid, 15, 0))
                player_rows.append(
                    (pid, None, None, 15) + (None,) * len(pl_rating)
                )
                items_rows.append(
                    (paid + "-it", paid, 0) + (None,) * len(it_rating)
                )
    player_cols = [
        "api_id", "rank_points_ranked", "rank_points_blitz", "skill_tier",
    ] + pl_rating
    items_cols = ["api_id", "participant_api_id", "any_afk"] + it_rating
    return {
        "match_rows": match_rows,
        "roster_rows": roster_rows,
        "part_rows": part_rows,
        "player_cols": player_cols,
        "player_rows": player_rows,
        "items_cols": items_cols,
        "items_rows": items_rows,
        "schema_rating_cols": {
            "player": pl_rating, "participant_items": it_rating,
        },
        "schema_columns": {
            "match": {"api_id", "game_mode", "created_at",
                      "trueskill_quality"},
            "participant": {"api_id", "trueskill_mu", "trueskill_sigma",
                            "trueskill_delta"},
            "player": set(player_cols),
            "participant_items": set(items_cols),
        },
    }


def synthetic_batch(n: int, team_size: int = 3, game_mode: str = "ranked",
                    id_prefix: str = "warm") -> list:
    """``n`` well-formed two-team matches of fresh tier-15 players, every
    player distinct — the worker's warmup probe input (never touches a
    store)."""
    matches = []
    for m in range(n):
        rosters = []
        for t in range(2):
            parts = [
                fake_participant(
                    player=fake_player(skill_tier=15),
                    skill_tier=15,
                )
                for _ in range(team_size)
            ]
            for s, part in enumerate(parts):
                part.player[0].api_id = f"{id_prefix}_{m}_{t}_{s}"
            rosters.append(fake_roster(winner=int(t == 0), participants=parts))
        match = fake_match(game_mode, rosters, api_id=f"{id_prefix}_m{m}")
        match.created_at = m
        matches.append(match)
    return matches
