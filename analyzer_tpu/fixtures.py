"""Duck-typed fake ORM object factories.

The reference's tests hand-build plain classes mirroring only the
attributes ``rate_match`` touches (``worker_test.py:6-63``) — no DB, no
broker, no mocks. These factories keep that strategy (SURVEY.md section 4
calls it the single most important design fact to preserve) but cover the
full 7-column rating schema, including the 5v5 pairs the reference's
fixtures omit.

They live in the package (not under ``tests/``) because production code
uses them too: the worker's warmup cost probe encodes a synthetic
batch-size object graph to measure per-batch host time
(``service/worker.py``). One definition keeps the probe and the parity
tests from drifting when the encoded attribute set changes.
"""

from __future__ import annotations

from types import SimpleNamespace

from analyzer_tpu.core.constants import RATING_COLUMNS


def fake_player(skill_tier=None, rank_points_ranked=None, rank_points_blitz=None,
                **ratings):
    attrs = {"api_id": "", "skill_tier": skill_tier,
             "rank_points_ranked": rank_points_ranked,
             "rank_points_blitz": rank_points_blitz}
    for col in RATING_COLUMNS:
        attrs[f"{col}_mu"] = None
        attrs[f"{col}_sigma"] = None
    attrs.update(ratings)
    return SimpleNamespace(**attrs)


def fake_items(**ratings):
    attrs = {"api_id": "", "any_afk": False}
    for col in RATING_COLUMNS[1:]:
        attrs[f"{col}_mu"] = None
        attrs[f"{col}_sigma"] = None
    attrs.update(ratings)
    return SimpleNamespace(**attrs)


def fake_participant(player=None, items=None, skill_tier=0, went_afk=False):
    return SimpleNamespace(
        api_id="",
        skill_tier=skill_tier,
        went_afk=went_afk,
        trueskill_mu=None,
        trueskill_sigma=None,
        trueskill_delta=None,
        participant_items=[items if items is not None else fake_items()],
        player=[player if player is not None else fake_player()],
    )


def fake_roster(winner, participants):
    return SimpleNamespace(api_id="", winner=winner, participants=participants)


def fake_match(game_mode, rosters, api_id=""):
    return SimpleNamespace(
        api_id=api_id,
        game_mode=game_mode,
        rosters=rosters,
        participants=[p for r in rosters for p in r.participants],
        trueskill_quality=None,
        created_at=0,
    )


def synthetic_batch(n: int, team_size: int = 3, game_mode: str = "ranked",
                    id_prefix: str = "warm") -> list:
    """``n`` well-formed two-team matches of fresh tier-15 players, every
    player distinct — the worker's warmup probe input (never touches a
    store)."""
    matches = []
    for m in range(n):
        rosters = []
        for t in range(2):
            parts = [
                fake_participant(
                    player=fake_player(skill_tier=15),
                    skill_tier=15,
                )
                for _ in range(team_size)
            ]
            for s, part in enumerate(parts):
                part.player[0].api_id = f"{id_prefix}_{m}_{t}_{s}"
            rosters.append(fake_roster(winner=int(t == 0), participants=parts))
        match = fake_match(game_mode, rosters, api_id=f"{id_prefix}_m{m}")
        match.created_at = m
        matches.append(match)
    return matches
