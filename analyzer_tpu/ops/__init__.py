from analyzer_tpu.ops.normal import cdf, log_pdf, v_win, w_win
from analyzer_tpu.ops.trueskill import (
    quality,
    two_team_update,
    win_probability,
)

__all__ = [
    "cdf",
    "log_pdf",
    "v_win",
    "w_win",
    "quality",
    "two_team_update",
    "win_probability",
]
