"""50-digit mpmath oracle for the closed-form TrueSkill update.

The reference runs its factor graph on mpmath at 50 decimal digits
(``rater.py:6-8,31``). For the two-team draw_probability=0 case the graph
converges to the closed form implemented in :mod:`analyzer_tpu.ops.trueskill`
— so this module IS the reference numerics, at reference precision, for
validating the float32 TPU kernels (SURVEY.md section 7, hard part #2:
"document achieved error vs a CPU oracle"). Host-side and slow by design;
used only by tests/test_oracle.py and never imported by the pipeline.
"""

from __future__ import annotations

import mpmath as mp

mp.mp.dps = 50  # the reference's precision (rater.py:8)


def _phi(t):
    return mp.exp(-t * t / 2) / mp.sqrt(2 * mp.pi)


def _Phi(t):
    return mp.erfc(-t / mp.sqrt(2)) / 2


def v_win(t):
    """phi(t)/Phi(t) at 50 digits."""
    t = mp.mpf(t)
    return _phi(t) / _Phi(t)


def w_win(t):
    t = mp.mpf(t)
    v = v_win(t)
    return v * (v + t)


def two_team_update(mu, sigma, winner, beta, tau):
    """Closed-form update for two teams of players at 50 digits.

    mu, sigma: nested lists [2][team_size] of priors.
    Returns (new_mu, new_sigma) with the same nesting.
    """
    beta = mp.mpf(beta)
    tau = mp.mpf(tau)
    s2 = [[mp.mpf(s) ** 2 + tau**2 for s in team] for team in sigma]
    n = sum(len(t) for t in mu)
    c2 = sum(sum(team) for team in s2) + n * beta**2
    c = mp.sqrt(c2)
    mu_w = sum(mp.mpf(m) for m in mu[winner])
    mu_l = sum(mp.mpf(m) for m in mu[1 - winner])
    t = (mu_w - mu_l) / c
    v = v_win(t)
    w = w_win(t)
    new_mu, new_sigma = [[], []], [[], []]
    for ti in range(2):
        sign = 1 if ti == winner else -1
        for si in range(len(mu[ti])):
            new_mu[ti].append(mp.mpf(mu[ti][si]) + sign * s2[ti][si] / c * v)
            new_sigma[ti].append(
                mp.sqrt(s2[ti][si] * (1 - s2[ti][si] / c2 * w))
            )
    return new_mu, new_sigma


def quality(mu, sigma, beta):
    """Two-team draw-probability quality at 50 digits (no tau inflation —
    matches trueskill's env.quality, rater.py:141)."""
    beta = mp.mpf(beta)
    n = sum(len(t) for t in mu)
    s2_sum = sum(sum(mp.mpf(s) ** 2 for s in team) for team in sigma)
    denom = n * beta**2 + s2_sum
    mu_diff = sum(mp.mpf(m) for m in mu[0]) - sum(mp.mpf(m) for m in mu[1])
    return mp.sqrt(n * beta**2 / denom) * mp.exp(-(mu_diff**2) / (2 * denom))
