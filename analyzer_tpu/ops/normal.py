"""Numerically stable standard-normal helpers for rating updates.

The TrueSkill win update needs the inverse Mills ratio v(t) = phi(t) / Phi(t)
and w(t) = v(t) * (v(t) + t). Naively dividing pdf by cdf underflows for
t << 0 (Phi(t) hits 0 in float32 around t = -12, long before real matchups
stop occurring at sigma0=1000 scale). The reference sidesteps this with
50-digit mpmath (``rater.py:8``) — three orders of magnitude too slow and not
TPU-expressible. We instead compute v in log space via ``log_ndtr``:

    v(t) = exp(log phi(t) - log Phi(t))

which is finite and accurate over the whole float range, and clamp w into its
mathematical range [0, 1]. Everything here is elementwise, fuses into the
surrounding update kernel, and runs on the VPU.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.special import log_ndtr, ndtr

_LOG_SQRT_2PI = 0.9189385332046727  # log(sqrt(2*pi))


def log_pdf(t: jnp.ndarray) -> jnp.ndarray:
    return -0.5 * t * t - _LOG_SQRT_2PI


def cdf(t: jnp.ndarray) -> jnp.ndarray:
    return ndtr(t)


def v_win(t: jnp.ndarray) -> jnp.ndarray:
    """phi(t)/Phi(t), stable for arbitrarily negative t.

    For t -> -inf, v(t) -> -t (the update saturates at "move the full
    surprise"); for t -> +inf, v(t) -> 0.
    """
    return jnp.exp(log_pdf(t) - log_ndtr(t))


def w_win(t: jnp.ndarray, v: jnp.ndarray | None = None) -> jnp.ndarray:
    """w(t) = v(t) * (v(t) + t), the variance-shrink factor, in (0, 1).

    Two regimes (bounds measured against the 50-digit mpmath oracle,
    tests/test_oracle.py):
      * t > -10: direct v*(v+t), clamped into [0, 1] (w -> 1 as t -> -inf
        and float cancellation can push it epsilon outside, which would
        make the posterior variance negative). Error < ~5e-4 at the -10
        boundary, < 2e-5 for t > -2 — and the physical regime here is
        |t| < 4 (t = mu_gap / c with c >= sqrt(n) * beta = 1000*sqrt(n)).
      * t <= -10: the direct form loses digits to cancellation (v ~ -t,
        v + t ~ -1/t), so use the asymptotic Mills-ratio series
        w = 1 - 1/t^2 + 6/t^4, accurate to < 5e-5 there and improving
        as t decreases.
    """
    if v is None:
        v = v_win(t)
    direct = jnp.clip(v * (v + t), 0.0, 1.0)
    # Guard the unselected lane: 1/t^2 at t=0 would be Inf (poisoning
    # jax_debug_nans and any future grad) even though where() discards it.
    tg = jnp.where(t <= -10.0, t, -10.0)
    t2 = tg * tg
    tail = 1.0 - 1.0 / t2 + 6.0 / (t2 * t2)
    return jnp.where(t <= -10.0, tail, direct)
