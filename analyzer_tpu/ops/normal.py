"""Numerically stable standard-normal helpers for rating updates.

The TrueSkill win update needs the inverse Mills ratio v(t) = phi(t) / Phi(t)
and w(t) = v(t) * (v(t) + t). Naively dividing pdf by cdf underflows for
t << 0 (Phi(t) hits 0 in float32 around t = -12, long before real matchups
stop occurring at sigma0=1000 scale). The reference sidesteps this with
50-digit mpmath (``rater.py:8``) — three orders of magnitude too slow and not
TPU-expressible. We instead compute v in log space via ``log_ndtr``:

    v(t) = exp(log phi(t) - log Phi(t))

which is finite and accurate over the whole float range, and clamp w into its
mathematical range [0, 1]. Everything here is elementwise, fuses into the
surrounding update kernel, and runs on the VPU.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.special import log_ndtr, ndtr

_LOG_SQRT_2PI = 0.9189385332046727  # log(sqrt(2*pi))


def log_pdf(t: jnp.ndarray) -> jnp.ndarray:
    return -0.5 * t * t - _LOG_SQRT_2PI


def cdf(t: jnp.ndarray) -> jnp.ndarray:
    return ndtr(t)


def v_win(t: jnp.ndarray) -> jnp.ndarray:
    """phi(t)/Phi(t), stable for arbitrarily negative t.

    For t -> -inf, v(t) -> -t (the update saturates at "move the full
    surprise"); for t -> +inf, v(t) -> 0.
    """
    return jnp.exp(log_pdf(t) - log_ndtr(t))


def w_win(t: jnp.ndarray, v: jnp.ndarray | None = None) -> jnp.ndarray:
    """w(t) = v(t) * (v(t) + t), the variance-shrink factor, in (0, 1).

    Clamped to [0, 1): w -> 1 as t -> -inf and float cancellation in
    v*(v+t) can otherwise push it epsilon outside the valid range, which
    would make the posterior variance negative.
    """
    if v is None:
        v = v_win(t)
    return jnp.clip(v * (v + t), 0.0, 1.0)
