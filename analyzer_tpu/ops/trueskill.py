"""Closed-form two-team TrueSkill kernels.

The reference rates matches through the generic trueskill 0.4.4 factor graph
run at 50-digit mpmath precision (``rater.py:30-37,141,144,161``) — iterative
Gaussian message passing per match on one CPU core. That design cannot run on
a TPU and does not need to: the reference only ever rates **two** teams
(``len(match.rosters) != 2`` is rejected, ``rater.py:91``) with
``draw_probability=0`` (``rater.py:36``), and for that case the factor graph
converges in a single pass to the closed-form update of Herbrich et al.'s
original TrueSkill paper:

    c^2   = sum_i (sigma_i^2 + tau^2) + n * beta^2      (all players, n total)
    t     = (mu_winners - mu_losers) / c
    v     = phi(t) / Phi(t)        w = v * (v + t)
    mu_i    <- mu_i +/- (sigma_i^2 + tau^2) / c * v     (+ winners, - losers)
    sigma_i <- sqrt((sigma_i^2 + tau^2) * (1 - (sigma_i^2 + tau^2) / c^2 * w))

This is a handful of elementwise VPU ops with two small reductions — exactly
vmappable over a match batch, fusable by XLA, and numerically safe in float32
via the log-space v/w in :mod:`analyzer_tpu.ops.normal` (replacing the
reference's 50-digit arbitrary precision).

Shape convention: per-slot arrays are ``[..., 2, T]`` — two teams of up to
``T`` padded player slots with a boolean ``mask`` selecting real players.
All functions broadcast over arbitrary leading batch dims.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from analyzer_tpu.config import RatingConfig
from analyzer_tpu.ops.normal import cdf, v_win, w_win

_TINY = 1e-20


def _masked_sum_stats(mu, sigma2, mask):
    """Returns (n, sigma2_sum, mu_diff) reduced over the (2, T) team axes."""
    maskf = mask.astype(mu.dtype)
    n = maskf.sum(axis=(-2, -1))
    sigma2_sum = (sigma2 * maskf).sum(axis=(-2, -1))
    team_mu = (mu * maskf).sum(axis=-1)  # [..., 2]
    mu_diff = team_mu[..., 0] - team_mu[..., 1]
    return n, sigma2_sum, mu_diff


def two_team_update(
    mu: jnp.ndarray,
    sigma: jnp.ndarray,
    mask: jnp.ndarray,
    winner: jnp.ndarray,
    cfg: RatingConfig,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """One TrueSkill win/loss update for a (batch of) two-team matches.

    Args:
      mu, sigma: prior ratings, ``[..., 2, T]``.
      mask: real-player mask, ``[..., 2, T]`` bool.
      winner: index (0 or 1) of the winning team, ``[...]`` int. Mirrors the
        reference's ``ranks=[int(not r.winner) ...]`` (``rater.py:144``):
        the roster with ``winner=True`` gets the better (lower) rank.
      cfg: TrueSkill environment (mu0/sigma0/beta/tau).

    Returns posterior (mu, sigma) with masked slots passed through unchanged.
    """
    dtype = mu.dtype
    tau2 = jnp.asarray(cfg.tau2, dtype)
    beta2 = jnp.asarray(cfg.beta2, dtype)

    s2 = sigma * sigma + tau2  # dynamics-inflated prior variance
    n, s2_sum, mu_diff = _masked_sum_stats(mu, s2, mask)
    c2 = jnp.maximum(s2_sum + n * beta2, _TINY)
    c = jnp.sqrt(c2)

    sign = (1 - 2 * winner).astype(dtype)  # +1 if team 0 won
    t = sign * mu_diff / c
    v = v_win(t)
    w = w_win(t, v)

    # +1 for every slot on the winning team, -1 on the losing team. The
    # +/-1 pair is generated from an iota instead of a captured [1, -1]
    # literal so the fused Pallas kernel (core/fused.py) can trace this
    # body — kernels cannot close over array constants. (2, 1) keeps the
    # iota >= 2-D for Mosaic; the +/-1 products are exact either way, so
    # the update is bit-identical to the constant form.
    team_pm = 1.0 - 2.0 * jax.lax.broadcasted_iota(dtype, (2, 1), 0)
    team_sign = sign[..., None, None] * team_pm  # [..., 2, 1]
    mu_new = mu + team_sign * (s2 / c[..., None, None]) * v[..., None, None]
    sigma_new = jnp.sqrt(s2 * (1.0 - (s2 / c2[..., None, None]) * w[..., None, None]))

    mu_new = jnp.where(mask, mu_new, mu)
    sigma_new = jnp.where(mask, sigma_new, sigma)
    return mu_new, sigma_new


def quality(
    mu: jnp.ndarray,
    sigma: jnp.ndarray,
    mask: jnp.ndarray,
    cfg: RatingConfig,
) -> jnp.ndarray:
    """Match-quality (draw-probability) score, ``env.quality`` equivalent.

    For one comparison row A = (1..1, -1..-1) the general matrix expression
    sqrt(det(beta^2 A A^T) / det(beta^2 A A^T + A Sigma A^T)) *
    exp(-1/2 mu^T A^T (...)^-1 A mu) collapses to

        q = sqrt(n beta^2 / D) * exp(-(mu_0 - mu_1)^2 / (2 D)),
        D = n beta^2 + sum_i sigma_i^2

    (no tau inflation — quality evaluates priors as-is, matching trueskill's
    ``env.quality`` called at ``rater.py:141``). Verified against the dense
    matrix formula in tests/test_trueskill_ops.py.
    """
    dtype = mu.dtype
    beta2 = jnp.asarray(cfg.beta2, dtype)
    n, s2_sum, mu_diff = _masked_sum_stats(mu, sigma * sigma, mask)
    denom = jnp.maximum(n * beta2 + s2_sum, _TINY)
    return jnp.sqrt(n * beta2 / denom) * jnp.exp(-(mu_diff * mu_diff) / (2.0 * denom))


def win_probability(
    mu: jnp.ndarray,
    sigma: jnp.ndarray,
    mask: jnp.ndarray,
    cfg: RatingConfig,
) -> jnp.ndarray:
    """P(team 0 beats team 1) = Phi((mu_0 - mu_1) / c), c^2 = sum sigma^2 + n beta^2.

    The reference has no explicit win-probability output; this is the
    closed-form head that BASELINE.json config 3 builds on (and the
    probability whose complement-symmetry is tested).
    """
    dtype = mu.dtype
    beta2 = jnp.asarray(cfg.beta2, dtype)
    n, s2_sum, mu_diff = _masked_sum_stats(mu, sigma * sigma, mask)
    c = jnp.sqrt(jnp.maximum(n * beta2 + s2_sum, _TINY))
    return cdf(mu_diff / c)
