"""Typed configuration with reference-compatible environment variables.

The reference configures everything through env vars read at import time:
rating hyperparameters at ``rater.py:10-11`` (``UNKNOWN_PLAYER_SIGMA`` default
500, ``TAU`` default 1000/100) and twelve service vars at ``worker.py:16-27``.
We keep the exact same variable names and defaults so a deployment can switch
frameworks without touching its environment, but read them into frozen
dataclasses instead of module globals, and validate once at construction.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Mapping


def _env(env: Mapping[str, str] | None) -> Mapping[str, str]:
    return os.environ if env is None else env


# Clamp range for the pipelined loop's commit lag (in-flight batches past
# the last known commit). The floor keeps at least one full round trip
# overlapped; the ceiling bounds the failure blast radius (an aborted
# stream reprocesses up to `lag` batches sequentially) and the broker's
# unacked-delivery headroom (`ServiceConfig.prefetch_count`).
PIPELINE_MIN_LAG = 2
PIPELINE_MAX_LAG = 12


@dataclasses.dataclass(frozen=True)
class RatingConfig:
    """TrueSkill environment hyperparameters.

    Defaults mirror the reference environment at ``rater.py:30-37``:
    mu0=1500, sigma0=1000, beta=10/30*3000=1000, tau=TAU, draw_probability=0.
    ``draw_probability`` must stay 0: the closed-form two-team kernel in
    :mod:`analyzer_tpu.ops.trueskill` exploits it (no draw margin).
    """

    mu0: float = 1500.0
    sigma0: float = 1000.0
    beta: float = 10.0 / 30.0 * 3000.0
    tau: float = 1000.0 / 100.0
    unknown_player_sigma: float = 500.0
    draw_probability: float = 0.0

    def __post_init__(self) -> None:
        if self.draw_probability != 0.0:
            raise ValueError(
                "analyzer_tpu implements the draw_probability=0 closed form "
                "(the reference fixes draw_probability=0 at rater.py:36)"
            )
        if self.beta <= 0 or self.sigma0 <= 0:
            raise ValueError("beta and sigma0 must be positive")

    @classmethod
    def from_env(cls, env: Mapping[str, str] | None = None) -> "RatingConfig":
        """Reads ``UNKNOWN_PLAYER_SIGMA`` and ``TAU`` like ``rater.py:10-11``
        (empty string falls back to the default, matching ``or``-defaults)."""
        e = _env(env)
        return cls(
            unknown_player_sigma=float(e.get("UNKNOWN_PLAYER_SIGMA") or 500),
            tau=float(e.get("TAU") or 1000 / 100.0),
        )

    @property
    def beta2(self) -> float:
        return self.beta * self.beta

    @property
    def tau2(self) -> float:
        return self.tau * self.tau


@dataclasses.dataclass(frozen=True)
class ServiceConfig:
    """Service-shell knobs, mirroring ``worker.py:16-27`` name-for-name.

    ``database_uri`` is required there (plain ``os.environ[...]`` KeyError at
    ``worker.py:17``); here it is optional because the in-memory store and the
    tensor pipeline do not need a database.
    """

    rabbitmq_uri: str = "amqp://localhost"
    database_uri: str | None = None
    batch_size: int = 500
    chunk_size: int = 100
    idle_timeout: float = 1.0
    queue: str = "analyze"
    do_crunch_match: bool = False
    crunch_queue: str = "crunch_global"
    do_telesuck_match: bool = False
    telesuck_queue: str = "telesuck"
    do_sew_match: bool = False
    sew_queue: str = "sew"
    # Not reference vars: the pipelined consume loop (service/pipeline.py).
    # Default False for direct construction (tests get the sequential,
    # reference-shaped loop); from_env defaults ON — production workers
    # want the overlap, and PIPELINE=false restores the sequential loop.
    # ``pipeline_lag=None`` means auto-tune: the worker measures the
    # dispatch->fetch round trip and its per-batch host time at warmup
    # and sets lag ~ ceil(RTT / host_time) + 1, clamped to
    # [PIPELINE_MIN_LAG, PIPELINE_MAX_LAG] (service/pipeline.py:
    # choose_pipeline_lag). Set PIPELINE_LAG to pin a fixed depth.
    pipeline: bool = False
    pipeline_lag: int | None = None

    @classmethod
    def from_env(cls, env: Mapping[str, str] | None = None) -> "ServiceConfig":
        e = _env(env)
        return cls(
            rabbitmq_uri=e.get("RABBITMQ_URI") or "amqp://localhost",
            database_uri=e.get("DATABASE_URI"),
            batch_size=int(e.get("BATCHSIZE") or 500),
            chunk_size=int(e.get("CHUNKSIZE") or 100),
            idle_timeout=float(e.get("IDLE_TIMEOUT") or 1),
            queue=e.get("QUEUE") or "analyze",
            do_crunch_match=e.get("DOCRUNCHMATCH") == "true",
            crunch_queue=e.get("CRUNCH_QUEUE") or "crunch_global",
            do_telesuck_match=e.get("DOTELESUCKMATCH") == "true",
            telesuck_queue=e.get("TELESUCK_QUEUE") or "telesuck",
            do_sew_match=e.get("DOSEWMATCH") == "true",
            sew_queue=e.get("SEW_QUEUE") or "sew",
            pipeline=(e.get("PIPELINE") or "true") == "true",
            pipeline_lag=(
                int(e["PIPELINE_LAG"]) if e.get("PIPELINE_LAG") else None
            ),
        )

    @property
    def failed_queue(self) -> str:
        return self.queue + "_failed"

    @property
    def prefetch_count(self) -> int:
        """AMQP prefetch bound for the broker connection.

        The reference pins ``prefetch_count=BATCHSIZE`` (``worker.py:91``)
        — right for the sequential loop, where at most one batch is ever
        unacked. The pipelined loop defers each batch's acks until its
        commit is harvested, so up to ``lag + 1`` batches are legitimately
        unacked at once; with only one batch-size of headroom the broker
        would withhold batch N+1's deliveries until batch N fully acked,
        serializing the pipeline back to the sequential loop. Auto-tuned
        lag sizes for the clamp ceiling (the measured lag is unknown at
        connect time; over-provisioned prefetch costs only broker-side
        buffering)."""
        if not self.pipeline:
            return self.batch_size
        # max(1, ...) mirrors PipelineEngine's own clamp: PIPELINE_LAG=0
        # still runs the engine at lag 1 (two batches legitimately
        # unacked), so prefetch must cover two.
        lag = (
            PIPELINE_MAX_LAG if self.pipeline_lag is None
            else max(1, self.pipeline_lag)
        )
        return self.batch_size * (lag + 1)
