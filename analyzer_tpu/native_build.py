"""Shared build-and-load helper for the native (C++) host components.

Both native extensions — the scheduler's first-fit assigner
(``sched/packer.cc``) and the CSV scanner (``io/fastcsv.cc``) — compile on
demand with g++ and load via ctypes (no pybind11 dependency). EVERY
failure mode surfaces as ImportError so callers' pure-python fallbacks
engage: missing g++, read-only package dir, a stale or corrupt ``.so``
(e.g. one rsync'd from another architecture — ctypes raises OSError for
an invalid ELF, which must not crash the program).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile


def sanitize_spec(env=None) -> tuple[str, list[str]]:
    """(filename tag, extra g++ flags) from ``ANALYZER_TPU_SANITIZE``.

    ``ANALYZER_TPU_SANITIZE=address,undefined`` compiles every native
    extension with ``-fsanitize=address,undefined -g
    -fno-omit-frame-pointer``. The tag lands in the ``.so`` name
    (``_packer.san-address-undefined.so``) so sanitized and normal builds
    never collide — flipping the env var always triggers a fresh build of
    the other flavor instead of silently reusing the wrong one.

    NOTE an ASan-instrumented ``.so`` only loads into a process with the
    ASan runtime already mapped (``LD_PRELOAD=$(g++ -print-file-name=
    libasan.so)``); without it the CDLL load fails and callers fall back
    to pure python like any other bad build. tests/test_native_sanitize.py
    runs the whole dance in a subprocess.

    ``ANALYZER_TPU_SANITIZE=thread`` builds under TSan for the concurrent
    hammer (``tests/sanitize_driver.py``). Thread may NOT be combined
    with address/leak: both runtimes interpose malloc with incompatible
    shadow-memory layouts, so a mixed build fails at load time with an
    opaque linker error — rejecting it here surfaces as the same
    ImportError the pure-python fallback contract expects, with a
    message that says why.
    """
    env = os.environ if env is None else env
    parts = [
        s.strip() for s in env.get("ANALYZER_TPU_SANITIZE", "").split(",")
        if s.strip()
    ]
    san = ",".join(parts)
    if not san:
        return "", []
    if "thread" in parts and ({"address", "leak"} & set(parts)):
        raise ImportError(
            "ANALYZER_TPU_SANITIZE cannot combine 'thread' with "
            "'address'/'leak': the TSan and ASan runtimes both interpose "
            "malloc with incompatible shadow memory and the mixed .so "
            "will not load — run the two drives as separate processes"
        )
    return (
        "san-" + san.replace(",", "-"),
        [f"-fsanitize={san}", "-g", "-fno-omit-frame-pointer"],
    )


def _compile(src: str, lib: str, extra_flags: list[str] = ()) -> None:
    """Atomic compile: temp name + rename, so concurrent importers either
    see the finished .so or rebuild harmlessly. Raises ImportError."""
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=os.path.dirname(lib))
        os.close(fd)
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
             *extra_flags, "-o", tmp, src],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, lib)
        tmp = None
    except (subprocess.CalledProcessError, OSError) as e:
        raise ImportError(f"native build failed for {src}: {e}") from e
    finally:
        if tmp is not None and os.path.exists(tmp):
            os.unlink(tmp)


def build_and_load(src: str, lib: str) -> ctypes.CDLL:
    """Compiles ``src`` to ``lib`` when missing/stale and returns the CDLL.
    Raises ImportError on ANY failure (build or load). Under
    ``ANALYZER_TPU_SANITIZE`` the library builds sanitized to a
    tag-suffixed path (see :func:`sanitize_spec`)."""
    tag, extra_flags = sanitize_spec()
    if tag:
        base, ext = os.path.splitext(lib)
        lib = f"{base}.{tag}{ext}"
    try:
        stale = not os.path.exists(lib) or (
            os.path.getmtime(lib) < os.path.getmtime(src)
        )
    except OSError as e:
        raise ImportError(f"native source unavailable: {e}") from e
    if stale:
        _compile(src, lib, extra_flags)
    try:
        return ctypes.CDLL(lib)
    except OSError as e:  # corrupt/foreign-arch .so — rebuild once, then give up
        try:
            os.unlink(lib)
        except OSError:
            pass
        _compile(src, lib, extra_flags)
        try:
            return ctypes.CDLL(lib)
        except OSError as e2:
            raise ImportError(
                f"native library unloadable: {e}; after rebuild: {e2}"
            ) from e2
